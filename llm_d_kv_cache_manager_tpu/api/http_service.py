"""HTTP scoring service — the shipped container entrypoint.

Parity target: /root/reference/examples/kv_events/online/main.go (the
reference's Dockerfile entrypoint): one process wiring the Indexer read path,
the ZMQ KVEvents write plane, and Prometheus metrics behind HTTP:

  POST /score_completions       {"prompt", "model", "pods"?} -> {"podScores"}
  POST /score_completions/batch {"requests": [{"prompt", "model", "pods"?,
                                 "lora_id"?}, ...]} -> {"results":
                                [{"podScores"}, ...]} — the whole batch
                                runs through Indexer.score_many (one
                                amortized read-path pass, per-item
                                results bit-identical to N single calls);
                                batch size capped by SCORE_BATCH_MAX
  POST /score_chat_completions  {"messages"/"conversations", "model",
                                 "chat_template"?, "pods"?}
                                -> {"podScores", "templated_messages"}
  GET  /metrics                 Prometheus exposition
  GET  /health                  liveness (the process is up, nothing more)
  GET  /readyz                  readiness: event-plane state (subscriber
                                thread + consecutive bind failures, shard
                                queue depths, drop counters), the per-pod
                                fleet-health summary, and the flight
                                recorder's own health (`obs` section);
                                503 while the event plane cannot make
                                progress
  GET  /cluster/status          replication introspection: this replica's
                                partition + readiness state and (when a
                                scatter-gather front is wired) per-replica
                                health
  GET  /routing/status          saturation-resilience introspection:
                                routing policy config/override stats +
                                per-pod load snapshot, admission gate
                                depth and shed counters
  POST /pod_load                pod-load reporter seam: {"pod",
                                "queue_depth"?, "inflight"?, "busy_s"?}
                                feeds the load_blend routing policy
                                (400 unless ROUTING_POLICY=load_blend)
  POST /cluster/snapshot        drain + write this replica's index
                                snapshot (view + seq watermarks) to
                                CLUSTER_SNAPSHOT_PATH
  GET  /federation/status       federation introspection: per-region
                                digest age + staleness state (healthy/
                                suspect/stale), stale regions, route/
                                failover/digest counters
  GET/POST /federation/score    two-level scoring entry: region pick over
                                shipped digests, precise delegation, pod
                                scores + region decision evidence
                                (same params as /score_completions plus
                                optional home_region)
  GET/POST /federation/digest   the digest shipping seam: GET builds this
                                region's encoded RegionDigest; POST
                                ingests a peer's
  GET  /prediction/status       anticipatory-prefetch introspection:
                                session-table occupancy/ETA evidence (the
                                soonest-expected sessions), misprediction
                                counters, and — when an embedder wires
                                them — the scheduler's policy stats and
                                the prefetch queue's per-source drops
  GET  /antientropy/status      index anti-entropy introspection: per-pod
                                advertised-vs-verified accuracy EWMA +
                                demotion factor, purge/readmit counters,
                                auditor + fetch-feedback stats when an
                                embedder wired them (also the /readyz
                                `index_health` section)
  GET  /slo/status              SLO burn-rate evaluation (obs/slo.py):
                                per-objective fast/slow-window burn
                                rates off the live registry, breach
                                status (also a /readyz `slo` section)
  GET  /autopilot/status        SLO-autopilot introspection (one
                                rate-limited controller tick, then the
                                document): knob positions vs baseline,
                                per-rule firing/breach evidence, the
                                recent actuation journal tail (also a
                                /readyz `autopilot` section)
  GET  /debug/traces            flight recorder dump: recent complete
                                traces + the slow-outlier reservoir.
                                Filters: ?limit= (alias n=), ?plane=,
                                ?min_ms=, ?trace_id= (exact 16-hex
                                distributed id), ?crit=1 attaches each
                                trace's critical-path breakdown
  GET  /debug/critical_path     window summary: per-(span, hop)
                                critical-path self-time over the recent
                                ring, grouped by root (?root= filters)
  GET  /debug/score_explain     score with the decision evidence attached
                                (per-pod matched prefix, fleet-health
                                adjustment, chain-memo family, chosen
                                pod); scores bit-identical to the scoring
                                endpoints. Query params prompt/model/
                                pods/lora_id, or POST the same JSON body
                                as /score_completions.

Env config mirrors the reference's variable set (online/main.go:41-58):
ZMQ_ENDPOINT, ZMQ_TOPIC, POOL_CONCURRENCY, PYTHONHASHSEED (hash seed!),
BLOCK_SIZE, BLOCK_HASH_ALGO, HTTP_PORT, HF_TOKEN, LOCAL_TOKENIZER_DIR,
the fleet-health windows SUSPECT_AFTER_S / STALE_AFTER_S, the tracing
spine knobs KVTPU_TRACE / KVTPU_TRACE_RING / KVTPU_TRACE_SLOW_MS /
KVTPU_TRACE_PROPAGATE, the SLO plane SLO / SLO_FAST_WINDOW_S /
SLO_SLOW_WINDOW_S / SLO_BURN_THRESHOLD / SLO_READ_P99_MS /
SLO_READ_BUDGET / SLO_HIT_RATE_FLOOR / SLO_SHED_RATE_CEILING, the
admission gate ADMISSION / ADMISSION_MAX_CONCURRENCY /
ADMISSION_QUEUE_DEPTH / ADMISSION_MAX_WAIT_MS / ADMISSION_RETRY_AFTER_MS
(scoring endpoints shed with 429 + Retry-After past the bounds; the
client's remaining budget propagates via the X-Request-Deadline-Ms
header), the load-aware routing policy ROUTING_POLICY /
ROUTING_LOAD_WEIGHT / ROUTING_QUEUE_NORM / ROUTING_BUSY_NORM_S /
ROUTING_PREEMPTION_NORM, the federation tier FEDERATION /
FEDERATION_REGION_ID / FEDERATION_REGIONS / FEDERATION_PEERS /
FEDERATION_DIGEST_INTERVAL_S / FEDERATION_DIGEST_SUSPECT_S /
FEDERATION_DIGEST_STALE_S, and the session predictor PREDICTION /
PREDICTION_MAX_SESSIONS / PREDICTION_ETA_ALPHA /
PREDICTION_MAX_CHAIN_BLOCKS / PREDICTION_DEFAULT_ETA_S (PREDICTION=0,
the default, keeps the read path byte-for-byte — the table is pure
observation even when on), and the index anti-entropy loop ANTIENTROPY /
ANTIENTROPY_ACCURACY_ALPHA / ANTIENTROPY_DISTRUST_THRESHOLD /
ANTIENTROPY_MIN_FACTOR / ANTIENTROPY_AUDIT_INTERVAL_S /
ANTIENTROPY_AUDIT_SAMPLE (ANTIENTROPY=0 default; on, scores stay
bit-identical while the fleet stays truthful — the tracker only demotes
on verified divergence), and the SLO autopilot AUTOPILOT /
AUTOPILOT_MIN_INTERVAL_S / AUTOPILOT_WARMUP_S / AUTOPILOT_COOLDOWN_S /
AUTOPILOT_DECAY_AFTER_S (AUTOPILOT=0 default; on with healthy signals,
every knob stays bit-identical to the operator's configuration — the
controller only actuates while an SLO burns, and walks every knob back
to baseline once it stops).

Run: python -m llm_d_kv_cache_manager_tpu.api.http_service
"""

from __future__ import annotations

import asyncio
import json
import os
from typing import Optional

from aiohttp import web

from llm_d_kv_cache_manager_tpu import obs
from llm_d_kv_cache_manager_tpu.api.admission import AdmissionRejected
from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.chain_memo import ChainMemoConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import IndexConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.fleethealth import (
    FleetHealthConfig,
    FleetHealthTracker,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import EventPool, EventPoolConfig
from llm_d_kv_cache_manager_tpu.metrics import collector as metrics_collector
from llm_d_kv_cache_manager_tpu.preprocessing.chat_completions import (
    ChatTemplatingProcessor,
    RenderRequest,
)
from llm_d_kv_cache_manager_tpu.tokenization.pool import TokenizersPoolConfig
from llm_d_kv_cache_manager_tpu.utils import logging as kvlog

logger = kvlog.get_logger("api.http")


def config_from_env() -> dict:
    return {
        "zmq_endpoint": os.environ.get("ZMQ_ENDPOINT", "tcp://*:5557"),
        "zmq_topic": os.environ.get("ZMQ_TOPIC", "kv@"),
        "pool_concurrency": int(os.environ.get("POOL_CONCURRENCY", "4")),
        "hash_seed": os.environ.get("PYTHONHASHSEED", ""),
        # "fnv64_cbor" (reference parity) or "sha256_cbor_64bit" (bit-exact
        # with vLLM --prefix-caching-hash-algo=sha256_cbor_64bit fleets).
        "hash_algo": os.environ.get("BLOCK_HASH_ALGO", "fnv64_cbor"),
        "block_size": int(os.environ.get("BLOCK_SIZE", "16")),
        # Chain-state memo (incremental block-key derivation). CHAIN_MEMO=0
        # pins the from-scratch path; keys are bit-identical either way.
        "chain_memo": os.environ.get("CHAIN_MEMO", "1") == "1",
        "chain_memo_capacity": int(
            os.environ.get("CHAIN_MEMO_CAPACITY", "131072")
        ),
        "http_port": int(os.environ.get("HTTP_PORT", "8080")),
        # Batched read path (score_many): the largest batch one
        # /score_completions/batch call (or one gRPC ScorePodsBulk
        # micro-batch window) may score, and how long the gRPC stream's
        # micro-batcher waits after a window's first item for stragglers
        # (0 = score whatever has arrived, never wait).
        "score_batch_max": int(os.environ.get("SCORE_BATCH_MAX", "128")),
        "score_batch_window_ms": float(
            os.environ.get("SCORE_BATCH_WINDOW_MS", "0")
        ),
        "hf_token": os.environ.get("HF_TOKEN"),
        "enable_hf": os.environ.get("ENABLE_HF_TOKENIZER", "") == "1",
        "enable_metrics": os.environ.get("ENABLE_METRICS", "1") == "1",
        # Shared index backend (redis:// or valkey:// URL) for multi-replica
        # managers; empty -> in-memory index.
        "index_url": os.environ.get("INDEX_URL", ""),
        # Native scoring core (kvcache/kvblock/native_index.py):
        # NATIVE_SCORING=1 backs the in-memory index with the C arena —
        # the whole read path (lookup + longest-prefix score + per-pod
        # adjustments) and event digestion each run in one GIL-released
        # crossing. Scores are bit-identical to the Python path (pinned
        # by the differential-fuzz suites); requires `make native`, and
        # silently degrades to the Python backend when the module isn't
        # built. Ignored when INDEX_URL selects a shared backend.
        "native_scoring": os.environ.get("NATIVE_SCORING", "0") == "1",
        # UDS tokenizer sidecar socket; empty -> local tokenization only.
        "uds_socket": os.environ.get("UDS_SOCKET", ""),
        # Fleet-health windows (fleethealth/tracker.py): event silence
        # beyond these demotes / excludes-and-purges a pod.
        "suspect_after_s": float(os.environ.get("SUSPECT_AFTER_S", "30")),
        "stale_after_s": float(os.environ.get("STALE_AFTER_S", "120")),
        # Tracing spine (obs/): per-request spans + flight recorder, plus
        # cross-process carrier propagation (obs/carrier.py) — off, every
        # process traces independently; scores identical either way.
        "trace_enabled": os.environ.get("KVTPU_TRACE", "1") == "1",
        "trace_ring": int(os.environ.get("KVTPU_TRACE_RING", "256")),
        "trace_slow_ms": float(os.environ.get("KVTPU_TRACE_SLOW_MS", "10")),
        "trace_propagate": os.environ.get("KVTPU_TRACE_PROPAGATE", "1") == "1",
        # SLO plane (obs/slo.py): declarative objectives evaluated from
        # the live Prometheus registry with fast+slow multi-window burn
        # rates (GET /slo/status, /readyz `slo` section,
        # kvcache_slo_burn_rate gauges). SLO=0 removes the monitor.
        "slo": os.environ.get("SLO", "1") == "1",
        "slo_fast_window_s": float(os.environ.get("SLO_FAST_WINDOW_S", "300")),
        "slo_slow_window_s": float(
            os.environ.get("SLO_SLOW_WINDOW_S", "3600")
        ),
        "slo_burn_threshold": float(
            os.environ.get("SLO_BURN_THRESHOLD", "2.0")
        ),
        "slo_read_p99_ms": float(os.environ.get("SLO_READ_P99_MS", "5")),
        "slo_read_budget": float(os.environ.get("SLO_READ_BUDGET", "0.01")),
        "slo_hit_rate_floor": float(
            os.environ.get("SLO_HIT_RATE_FLOOR", "0.5")
        ),
        "slo_shed_rate_ceiling": float(
            os.environ.get("SLO_SHED_RATE_CEILING", "0.01")
        ),
        # Replicated control plane (cluster/): this process's membership in
        # the logical index. CLUSTER_REPLICAS=1 (default) is the monolithic
        # deployment — no partition gate, no replication section.
        "cluster_replicas": int(os.environ.get("CLUSTER_REPLICAS", "1")),
        "cluster_replica_id": int(os.environ.get("CLUSTER_REPLICA_ID", "0")),
        "cluster_snapshot_path": os.environ.get("CLUSTER_SNAPSHOT_PATH", ""),
        # Predictive placement (placement/): PLACEMENT=1 attaches the
        # hot-prefix popularity tracker to the read path, the event pool,
        # and (when the backends support it) the instrumented/cost-aware
        # index — observation only; scores stay bit-identical. PLACEMENT=0
        # (default) leaves every hook None.
        "placement": os.environ.get("PLACEMENT", "0") == "1",
        "placement_top_k": int(os.environ.get("PLACEMENT_TOP_K", "64")),
        "placement_half_life_s": float(
            os.environ.get("PLACEMENT_HALF_LIFE_S", "120")
        ),
        "placement_hotness": float(
            os.environ.get("PLACEMENT_HOTNESS", "30")
        ),
        # Hierarchical federation (federation/): FEDERATION=1 attaches a
        # GlobalRouter over this region's indexer (+ the popularity
        # tracker the digests ship) and opens the /federation/* surface.
        # Peers are other regions' scoring fronts ("region=host:port",
        # reached over the same gRPC transport the cluster scatter-gather
        # uses). Single-region (no FEDERATION_REGIONS) stays pinned
        # bit-identical to the flat read path.
        "federation": os.environ.get("FEDERATION", "0") == "1",
        "federation_region_id": os.environ.get(
            "FEDERATION_REGION_ID", "region-0"
        ),
        "federation_regions": [
            r for r in os.environ.get("FEDERATION_REGIONS", "").split(",")
            if r
        ],
        "federation_peers": os.environ.get("FEDERATION_PEERS", ""),
        "federation_digest_interval_s": float(
            os.environ.get("FEDERATION_DIGEST_INTERVAL_S", "5")
        ),
        "federation_digest_suspect_s": float(
            os.environ.get("FEDERATION_DIGEST_SUSPECT_S", "15")
        ),
        "federation_digest_stale_s": float(
            os.environ.get("FEDERATION_DIGEST_STALE_S", "45")
        ),
        # Admission control (api/admission.py): bounded concurrency +
        # bounded waiting line on the scoring endpoints; past the bounds
        # requests are shed with 429 + Retry-After instead of queueing
        # without limit. ADMISSION=0 removes the gate entirely.
        "admission": os.environ.get("ADMISSION", "1") == "1",
        "admission_max_concurrency": int(
            os.environ.get("ADMISSION_MAX_CONCURRENCY", "8")
        ),
        "admission_queue_depth": int(
            os.environ.get("ADMISSION_QUEUE_DEPTH", "64")
        ),
        "admission_max_wait_ms": float(
            os.environ.get("ADMISSION_MAX_WAIT_MS", "1000")
        ),
        "admission_retry_after_ms": float(
            os.environ.get("ADMISSION_RETRY_AFTER_MS", "1000")
        ),
        # Load-aware routing policy (kvcache/routing.py): prefix_only
        # (default) is pinned bit-identical to the pure prefix read path;
        # load_blend divides each pod's prefix score by its normalized
        # load (queue depth / busy seconds / decayed preemption rate, fed
        # by POST /pod_load reports and the kvevents BlockRemoved stream).
        "routing_policy": os.environ.get("ROUTING_POLICY", "prefix_only"),
        "routing_load_weight": float(
            os.environ.get("ROUTING_LOAD_WEIGHT", "1.0")
        ),
        "routing_queue_norm": float(
            os.environ.get("ROUTING_QUEUE_NORM", "4.0")
        ),
        "routing_busy_norm_s": float(
            os.environ.get("ROUTING_BUSY_NORM_S", "1.0")
        ),
        "routing_preemption_norm": float(
            os.environ.get("ROUTING_PREEMPTION_NORM", "8.0")
        ),
        # Anticipatory prefetch (prediction/): PREDICTION=1 attaches the
        # session predictor's table at the read-path observation seam.
        # Observation only — scores stay bit-identical; the prefetch
        # scheduler itself needs a prefetch plane to the engine fleet, so
        # embedders wire a PrefetchScheduler + RoutePrefetcher and assign
        # them to `self.prefetch_scheduler` / `self.route_prefetcher` to
        # surface through /prediction/status and /readyz. PREDICTION=0
        # (default) leaves the seam None.
        "prediction": os.environ.get("PREDICTION", "0") == "1",
        "prediction_max_sessions": int(
            os.environ.get("PREDICTION_MAX_SESSIONS", "1024")
        ),
        "prediction_eta_alpha": float(
            os.environ.get("PREDICTION_ETA_ALPHA", "0.4")
        ),
        "prediction_max_chain_blocks": int(
            os.environ.get("PREDICTION_MAX_CHAIN_BLOCKS", "256")
        ),
        "prediction_default_eta_s": float(
            os.environ.get("PREDICTION_DEFAULT_ETA_S", "8")
        ),
        # Index anti-entropy (antientropy/): ANTIENTROPY=1 attaches the
        # per-pod trust tracker at the score-filter seam (truth-weighted
        # demotion; bit-identical while the fleet stays truthful) and the
        # orphan-removal probe in the event pool. The residency auditor
        # and fetch-miss feedback need pod digest / data-plane seams only
        # an embedder owns — assign to `self.auditor` /
        # `self.fetch_feedback` to surface them through /readyz
        # `index_health`. ANTIENTROPY=0 (default) leaves every hook None.
        "antientropy": os.environ.get("ANTIENTROPY", "0") == "1",
        "antientropy_accuracy_alpha": float(
            os.environ.get("ANTIENTROPY_ACCURACY_ALPHA", "0.3")
        ),
        "antientropy_distrust_threshold": float(
            os.environ.get("ANTIENTROPY_DISTRUST_THRESHOLD", "0.9")
        ),
        "antientropy_min_factor": float(
            os.environ.get("ANTIENTROPY_MIN_FACTOR", "0.25")
        ),
        "antientropy_audit_interval_s": float(
            os.environ.get("ANTIENTROPY_AUDIT_INTERVAL_S", "10")
        ),
        "antientropy_audit_sample": int(
            os.environ.get("ANTIENTROPY_AUDIT_SAMPLE", "16")
        ),
        # SLO autopilot (autopilot/): AUTOPILOT=1 attaches the closed-loop
        # controller over whatever knobs this process's subsystems
        # publish (the admission gate at minimum; embedder-wired
        # subsystems register theirs against `service.autopilot_registry`).
        # Ticks ride the /autopilot/status and /readyz poll cadence — no
        # background thread. AUTOPILOT=0 (default) leaves the plane None;
        # on with healthy signals, every knob stays bit-identical to the
        # operator's configuration.
        "autopilot": os.environ.get("AUTOPILOT", "0") == "1",
        "autopilot_min_interval_s": float(
            os.environ.get("AUTOPILOT_MIN_INTERVAL_S", "1")
        ),
        "autopilot_warmup_s": float(
            os.environ.get("AUTOPILOT_WARMUP_S", "10")
        ),
        "autopilot_cooldown_s": float(
            os.environ.get("AUTOPILOT_COOLDOWN_S", "5")
        ),
        "autopilot_decay_after_s": float(
            os.environ.get("AUTOPILOT_DECAY_AFTER_S", "15")
        ),
        # Resource governor (resourcegov/): RESOURCEGOV=1 attaches the
        # accountant + pressure state machine over every stateful
        # structure this construction wired. Ticks ride the /readyz and
        # /resource/status poll cadence — no background thread. The
        # departed-entity reaper is attached unconditionally (membership
        # leaves must shrink per-pod maps with or without a budget).
        # RESOURCEGOV=0 (default) leaves the governor None; a fleet that
        # never crosses the budget sheds nothing and scores
        # bit-identically either way.
        "resourcegov": os.environ.get("RESOURCEGOV", "0") == "1",
        "resourcegov_budget_mb": float(
            os.environ.get("RESOURCEGOV_BUDGET_MB", "256")
        ),
        "resourcegov_cooldown_s": float(
            os.environ.get("RESOURCEGOV_COOLDOWN_S", "10")
        ),
        "resourcegov_rss_probe": (
            os.environ.get("RESOURCEGOV_RSS_PROBE", "0") == "1"
        ),
    }


class _LazyStatusSource:
    """SignalAssembler source resolving its target per snapshot — the
    autopilot can see subsystems an embedder wires AFTER the service is
    constructed (route prefetcher, transfer client) without re-wiring."""

    def __init__(self, resolve):
        self._resolve = resolve

    def status(self) -> dict:
        target = self._resolve()
        return target.status() if target is not None else {}


def _peek_transfer_client():
    from llm_d_kv_cache_manager_tpu.kv_connectors import (
        connector as conn_mod,
    )

    return conn_mod.peek_default_client()


class ScoringService:
    """Owns the Indexer (read path) + EventPool (write plane)."""

    def __init__(
        self,
        env: Optional[dict] = None,
        indexer: Optional[Indexer] = None,
        cluster_replica=None,
    ):
        env = env or config_from_env()
        self.env = env
        # Tracing spine knobs (obs/). Only reconfigure when the env spells
        # them out — embedded/test construction respects whatever the
        # process already configured.
        if "trace_enabled" in env:
            obs.configure(obs.ObsConfig(
                enabled=bool(env.get("trace_enabled", True)),
                ring_capacity=int(env.get("trace_ring", 256)),
                slow_threshold_s=float(env.get("trace_slow_ms", 10)) / 1e3,
                propagate=bool(env.get("trace_propagate", True)),
            ))
        self.templating = ChatTemplatingProcessor()
        self.fleet_health = FleetHealthTracker(FleetHealthConfig(
            suspect_after_s=float(env.get("suspect_after_s", 30.0)),
            stale_after_s=float(env.get("stale_after_s", 120.0)),
        ))
        self._started = False

        # Admission gate (api/admission.py): one controller shared by
        # every scoring endpoint (and handed to serve_grpc when a gRPC
        # front is started next to this service), so the process has ONE
        # bounded budget rather than per-transport invisible queues.
        self.admission = None
        if env.get("admission", True):
            from llm_d_kv_cache_manager_tpu.api.admission import (
                AdmissionConfig,
                AdmissionController,
            )

            self.admission = AdmissionController(AdmissionConfig(
                max_concurrency=int(env.get("admission_max_concurrency", 8)),
                max_queue_depth=int(env.get("admission_queue_depth", 64)),
                max_wait_s=float(env.get("admission_max_wait_ms", 1000)) / 1e3,
                retry_after_s=(
                    float(env.get("admission_retry_after_ms", 1000)) / 1e3
                ),
            ))

        # SLO plane (obs/slo.py): one monitor over the live Prometheus
        # registry. Evaluation is pull-based (/slo/status, /readyz, the
        # scrape cadence); no background thread. A breach never gates
        # readiness — it is an alert, not a liveness failure.
        self.slo = None
        if env.get("slo", True):
            from llm_d_kv_cache_manager_tpu.obs.slo import (
                SLOConfig,
                SLOMonitor,
                default_objectives,
            )

            slo_config = SLOConfig(
                fast_window_s=float(env.get("slo_fast_window_s", 300.0)),
                slow_window_s=float(env.get("slo_slow_window_s", 3600.0)),
                burn_threshold=float(env.get("slo_burn_threshold", 2.0)),
                read_p99_ms=float(env.get("slo_read_p99_ms", 5.0)),
                read_latency_budget=float(env.get("slo_read_budget", 0.01)),
                hit_rate_floor=float(env.get("slo_hit_rate_floor", 0.5)),
                shed_rate_ceiling=float(
                    env.get("slo_shed_rate_ceiling", 0.01)
                ),
            )
            self.slo = SLOMonitor(default_objectives(slo_config), slo_config)

        # Load-aware routing policy (kvcache/routing.py +
        # fleethealth/load.py). The load tracker exists whenever the
        # policy does — load_blend without signals degrades to the
        # identity, so wiring order can't flip routing.
        self.load_tracker = None
        self.routing_policy = None
        policy_name = env.get("routing_policy", "prefix_only")
        if policy_name != "prefix_only":
            from llm_d_kv_cache_manager_tpu.fleethealth import PodLoadTracker
            from llm_d_kv_cache_manager_tpu.kvcache.routing import (
                RoutingPolicy,
                RoutingPolicyConfig,
            )

            self.load_tracker = PodLoadTracker()
            self.routing_policy = RoutingPolicy(
                RoutingPolicyConfig(
                    policy=policy_name,
                    load_weight=float(env.get("routing_load_weight", 1.0)),
                    queue_depth_norm=float(
                        env.get("routing_queue_norm", 4.0)
                    ),
                    busy_norm_s=float(env.get("routing_busy_norm_s", 1.0)),
                    preemption_norm=float(
                        env.get("routing_preemption_norm", 8.0)
                    ),
                ),
                load_tracker=self.load_tracker,
            )

        if indexer is not None:  # injected (tests / embedding)
            self.indexer = indexer
        else:
            index_config = IndexConfig.default()
            # Native scoring core only applies to the in-memory backend;
            # a shared-backend INDEX_URL wins (redis_config takes priority
            # in the backend-selection order below).
            index_config.native = bool(env.get("native_scoring", False))
            if env.get("index_url"):
                from llm_d_kv_cache_manager_tpu.kvcache.kvblock.redis_index import (
                    RedisIndexConfig,
                )

                index_config = IndexConfig(
                    redis_config=RedisIndexConfig(url=env["index_url"])
                )
            indexer_config = IndexerConfig(
                token_processor_config=TokenProcessorConfig(
                    block_size=env["block_size"],
                    hash_seed=env["hash_seed"],
                    hash_algo=env.get("hash_algo", "fnv64_cbor"),
                    chain_memo=env.get("chain_memo", True),
                    chain_memo_config=ChainMemoConfig(
                        capacity=env.get("chain_memo_capacity", 131072),
                    ),
                ),
                kv_block_index_config=index_config,
                tokenizers_pool_config=TokenizersPoolConfig(
                    enable_local=True,
                    enable_uds=bool(env.get("uds_socket")),
                    uds_socket_path=env.get("uds_socket") or None,
                    enable_hf=env["enable_hf"],
                    hf_auth_token=env.get("hf_token"),
                ),
            )
            indexer_config.kv_block_index_config.enable_metrics = env["enable_metrics"]
            self.indexer = Indexer(
                config=indexer_config, chat_templating=self.templating
            )

        # Wire fleet health into the read path (degraded-mode scoring) and
        # the quarantine target. Injected indexers get the same treatment —
        # their scores must also stop following phantom placements.
        if self.indexer.fleet_health is None:
            self.indexer.fleet_health = self.fleet_health
        if self.fleet_health.index is None:
            self.fleet_health.bind_index(self.indexer.kv_block_index)
        # Routing policy rides AFTER fleet-health filtering in the read
        # path (kvcache/indexer.py): health decides what is trustworthy,
        # the policy decides what is affordable. Injected indexers get the
        # same treatment unless they brought their own.
        if self.routing_policy is not None and self.indexer.routing_policy is None:
            self.indexer.routing_policy = self.routing_policy

        # Replicated deployments wrap the event pool in an IndexerReplica:
        # the pool gains the partition-ownership gate, and the service
        # gains the snapshot/warm-restart surface plus the `replaying`
        # readiness state. A single-replica config keeps the monolithic
        # wiring byte-for-byte (IndexerReplica passes message_filter=None).
        pool_config = EventPoolConfig(
            zmq_endpoint=env["zmq_endpoint"],
            topic_filter=env["zmq_topic"],
            concurrency=env["pool_concurrency"],
        )
        self.replica = None
        if cluster_replica is not None:
            self.replica = cluster_replica
            self.event_pool = cluster_replica.event_pool
        elif (
            int(env.get("cluster_replicas", 1)) > 1
            or env.get("cluster_snapshot_path")
        ):
            from llm_d_kv_cache_manager_tpu.cluster import (
                ClusterConfig,
                IndexerReplica,
            )

            self.replica = IndexerReplica(
                self.indexer,
                ClusterConfig(
                    num_replicas=int(env.get("cluster_replicas", 1)),
                    replica_id=int(env.get("cluster_replica_id", 0)),
                    snapshot_path=env.get("cluster_snapshot_path", ""),
                ),
                pool_config=pool_config,
                health_tracker=self.fleet_health,
            )
            self.event_pool = self.replica.event_pool
        else:
            self.event_pool = EventPool(
                pool_config,
                self.indexer.kv_block_index,
                self.indexer.token_processor,
                health_tracker=self.fleet_health,
            )
        # The kvevents write plane feeds the load tracker's preemption-
        # pressure signal: per-pod BlockRemoved volume is the wire-visible
        # trace of page-pool churn (observation only — digestion and
        # scores are untouched).
        if self.load_tracker is not None:
            self.event_pool.load_tracker = self.load_tracker

        # Index anti-entropy (antientropy/): ANTIENTROPY=1 attaches the
        # trust tracker at the indexer's score-filter seam and the event
        # pool's orphan-removal probe. The auditor / fetch-miss feedback
        # are embedder-wired (they need the pod digest surface and the
        # data-plane client) and surface through /readyz `index_health`.
        self.antientropy = None
        self.auditor = None
        self.fetch_feedback = None
        if env.get("antientropy"):
            from llm_d_kv_cache_manager_tpu.antientropy import (
                AntiEntropyConfig,
                AntiEntropyTracker,
            )

            self.antientropy = AntiEntropyTracker(AntiEntropyConfig(
                accuracy_alpha=float(
                    env.get("antientropy_accuracy_alpha", 0.3)
                ),
                distrust_threshold=float(
                    env.get("antientropy_distrust_threshold", 0.9)
                ),
                min_factor=float(env.get("antientropy_min_factor", 0.25)),
            ))
            self.indexer.antientropy = self.antientropy
            self.event_pool.divergence = self.antientropy
        # Optional scatter-gather front (embedders wire a ClusterScorer
        # over peer replicas); surfaces through /cluster/status only.
        self.cluster_scorer = None

        # Predictive placement (placement/): PLACEMENT=1 attaches the
        # popularity tracker at every ingest seam this process owns. The
        # replicator itself needs a prefetch plane to the engine fleet —
        # embedders wire a HotPrefixReplicator over their RoutePrefetcher
        # and assign it to `self.replicator` to surface through
        # /placement/status.
        self.popularity = None
        self.replicator = None
        if env.get("placement"):
            from llm_d_kv_cache_manager_tpu.placement import (
                ChainPopularityTracker,
                PopularityConfig,
            )

            self.popularity = ChainPopularityTracker(PopularityConfig(
                top_k=int(env.get("placement_top_k", 64)),
                half_life_s=float(env.get("placement_half_life_s", 120.0)),
            ))
            self.indexer.popularity = self.popularity
            self.event_pool.popularity = self.popularity
            index = self.indexer.kv_block_index
            if hasattr(index, "popularity"):  # InstrumentedIndex wrapper
                index.popularity = self.popularity
                index = index.inner
            if hasattr(index, "bind_popularity"):  # cost-aware backend
                index.bind_popularity(self.popularity)

        # Anticipatory prefetch (prediction/): PREDICTION=1 attaches the
        # session table at the read-path observation seam. The scheduler
        # and its prefetch plane are embedder-wired (like the placement
        # replicator) — assign to `prefetch_scheduler`/`route_prefetcher`
        # to surface them through /prediction/status and /readyz.
        self.session_table = None
        self.prefetch_scheduler = None
        self.route_prefetcher = None
        # Data-plane client for the /readyz `transfer` section. Embedders
        # that own a KVConnector assign its TransferClient here; otherwise
        # the section reports the process-wide default client if (and only
        # if) something in this process created one.
        self.transfer_client = None
        if env.get("prediction"):
            from llm_d_kv_cache_manager_tpu.prediction import (
                PredictionConfig,
                SessionTable,
            )

            self.session_table = SessionTable(PredictionConfig(
                max_sessions=int(env.get("prediction_max_sessions", 1024)),
                eta_alpha=float(env.get("prediction_eta_alpha", 0.4)),
                max_chain_blocks=int(
                    env.get("prediction_max_chain_blocks", 256)
                ),
                default_eta_s=float(
                    env.get("prediction_default_eta_s", 8.0)
                ),
            ))
            self.indexer.prediction = self.session_table

        # Hierarchical federation (federation/): this process becomes one
        # region of a global fleet. The local region wraps THIS indexer;
        # peer regions are reached over the cluster gRPC transport.
        # Digests ship pull-style through GET/POST /federation/digest —
        # no background thread in the service; a sidecar (or the peer
        # itself) moves the bytes on whatever cadence it owns.
        self.federation = None
        if env.get("federation"):
            from llm_d_kv_cache_manager_tpu.federation import (
                FederationConfig,
                GlobalRouter,
                Region,
                derive_fn_from_indexer,
            )
            from llm_d_kv_cache_manager_tpu.placement import (
                ChainPopularityTracker,
                PopularityConfig,
            )

            if self.popularity is None:
                # Digests ship the popularity sketch; federation without
                # placement still needs the observation-only tracker.
                self.popularity = ChainPopularityTracker(PopularityConfig(
                    top_k=int(env.get("placement_top_k", 64)),
                    half_life_s=float(
                        env.get("placement_half_life_s", 120.0)
                    ),
                ))
                self.indexer.popularity = self.popularity
                self.event_pool.popularity = self.popularity
            fed_config = FederationConfig(
                region_id=env.get("federation_region_id", "region-0"),
                regions=list(env.get("federation_regions", [])),
                digest_interval_s=float(
                    env.get("federation_digest_interval_s", 5.0)
                ),
                digest_suspect_after_s=float(
                    env.get("federation_digest_suspect_s", 15.0)
                ),
                digest_stale_after_s=float(
                    env.get("federation_digest_stale_s", 45.0)
                ),
            )
            regions = {
                fed_config.region_id: Region(
                    fed_config.region_id,
                    self.indexer,
                    tracker=self.popularity,
                    pods_fn=lambda: list(
                        self.fleet_health.summary()["pods"]
                    ),
                )
            }
            peers = env.get("federation_peers", "")
            if peers:
                from llm_d_kv_cache_manager_tpu.cluster.scorer import (
                    GrpcReplicaTransport,
                )

                for spec in peers.split(","):
                    if not spec.strip():
                        continue
                    region_id, _, target = spec.partition("=")
                    if not target:
                        raise ValueError(
                            f"FEDERATION_PEERS entry {spec!r} is not "
                            "region=host:port"
                        )
                    regions[region_id.strip()] = Region(
                        region_id.strip(),
                        GrpcReplicaTransport(target.strip()),
                    )
            self.federation = GlobalRouter(
                fed_config,
                regions,
                derive_fn=derive_fn_from_indexer(self.indexer),
            )

        # SLO autopilot (autopilot/): AUTOPILOT=1 wires the closed-loop
        # controller LAST, over whatever this construction attached. The
        # admission gate publishes its knob here; embedder-wired
        # subsystems (replicator, prefetch scheduler, auditor, transfer
        # client) publish theirs by calling
        # `x.register_knobs(service.autopilot_registry)` after assigning
        # them — knobs registered later are immediately reachable by the
        # rules. Signal sources that arrive late (route prefetcher,
        # transfer client) are resolved lazily per snapshot.
        self.autopilot = None
        self.autopilot_registry = None
        self.autopilot_signals = None
        if env.get("autopilot"):
            from llm_d_kv_cache_manager_tpu.autopilot import (
                AutopilotConfig,
                AutopilotController,
                KnobRegistry,
                SignalAssembler,
            )

            self.autopilot_registry = KnobRegistry()
            if self.admission is not None:
                self.admission.register_knobs(self.autopilot_registry)
            assembler = SignalAssembler(
                slo_monitor=self.slo,
                load_tracker=self.load_tracker,
                transfer_client=_LazyStatusSource(
                    lambda: self.transfer_client
                    or _peek_transfer_client()
                ),
                antientropy=_LazyStatusSource(lambda: self.antientropy),
                prefetchers={
                    "route": _LazyStatusSource(
                        lambda: self.route_prefetcher
                    ),
                },
            )
            # Kept visible so the resourcegov block (wired below) can
            # attach itself as the memory_pressure source.
            self.autopilot_signals = assembler
            self.autopilot = AutopilotController(
                self.autopilot_registry,
                assembler,
                config=AutopilotConfig(
                    min_interval_s=float(
                        env.get("autopilot_min_interval_s", 1.0)
                    ),
                    warmup_s=float(env.get("autopilot_warmup_s", 10.0)),
                    cooldown_s=float(env.get("autopilot_cooldown_s", 5.0)),
                    decay_after_s=float(
                        env.get("autopilot_decay_after_s", 15.0)
                    ),
                ),
            )

        # Resource governance (resourcegov/): two planes with different
        # opt-ins. The departed-entity REAPER is always constructed —
        # per-pod rows must be able to die with their pod whether or not
        # a byte budget is configured (a leak fix, not a pressure
        # policy). Embedders that own a FleetMembership attach it with
        # `membership.reaper = service.reaper`; under RESOURCEGOV=1 the
        # fleet-health stale-quarantine path fans out through it too.
        # The GOVERNOR (accountant + pressure state machine + shed
        # ladder) attaches only under RESOURCEGOV=1, metering exactly
        # the structures this construction wired; its ticks ride the
        # /readyz and /resource/status poll cadence — no background
        # thread.
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import (
            base_pod_identifier,
        )
        from llm_d_kv_cache_manager_tpu.resourcegov import DepartureReaper

        self.reaper = DepartureReaper()
        self.reaper.register("fleethealth", self.fleet_health.forget_pod)
        if self.load_tracker is not None:
            self.reaper.register("load", self.load_tracker.forget_pod)
        if self.antientropy is not None:
            self.reaper.register(
                "antientropy", self.antientropy.forget_pod
            )

        def _reap_transfer(pod_identifier: str) -> int:
            # Resolved per reap, like the autopilot's lazy sources: the
            # transfer client usually appears after construction. Peer
            # addressing uses the base pod identity as the host.
            client = self.transfer_client or _peek_transfer_client()
            if client is None:
                return 0
            return client.forget_host(base_pod_identifier(pod_identifier))

        self.reaper.register("transfer", _reap_transfer)

        self.resourcegov = None
        self.resource_accountant = None
        if env.get("resourcegov"):
            from llm_d_kv_cache_manager_tpu.resourcegov import (
                STRUCT_ANTIENTROPY,
                STRUCT_CHAIN_MEMO,
                STRUCT_FLEETHEALTH,
                STRUCT_INDEX,
                STRUCT_LOAD,
                STRUCT_OBS,
                STRUCT_POPULARITY,
                STRUCT_PREFIX_STORE,
                STRUCT_SESSIONS,
                STRUCT_TRANSFER_PEERS,
                Meter,
                ResourceAccountant,
                ResourceGovConfig,
                ResourceGovernor,
            )

            accountant = ResourceAccountant()
            recorder = obs.get_recorder()
            accountant.register(Meter(
                STRUCT_OBS, recorder.entries,
                bytes_per_entry=2048.0, shed=recorder.shed,
            ))
            if self.session_table is not None:
                accountant.register(Meter(
                    STRUCT_SESSIONS, self.session_table.sessions,
                    bytes_per_entry=512.0, shed=self.session_table.shed,
                ))
            if self.popularity is not None:
                sketch = self.popularity.sketch
                accountant.register(Meter(
                    STRUCT_POPULARITY, self.popularity.entries,
                    bytes_per_entry=256.0,
                    fixed_bytes=float(sketch.width * sketch.depth * 8),
                    shed=self.popularity.shed,
                ))
            memo = self.indexer.token_processor.chain_memo
            if memo is not None:
                accountant.register(Meter(
                    STRUCT_CHAIN_MEMO, memo.entries,
                    bytes_per_entry=256.0, shed=memo.shed,
                ))
            store = getattr(self.indexer, "prefix_store", None)
            if store is not None and hasattr(store, "shed"):
                accountant.register(Meter(
                    STRUCT_PREFIX_STORE, store.entries,
                    bytes_per_entry=4096.0, shed=store.shed,
                ))
            index = self.indexer.kv_block_index
            inner = getattr(index, "inner", index)

            def _index_entries() -> int:
                sizes = getattr(inner, "segment_sizes", None)
                if callable(sizes):
                    return sum(sizes())
                data = getattr(inner, "_data", None)
                return len(data) if data is not None else 0

            accountant.register(Meter(
                STRUCT_INDEX, _index_entries,
                bytes_per_entry=1024.0,
                shed=getattr(inner, "shed", None),
            ))
            accountant.register(Meter(
                STRUCT_FLEETHEALTH, self.fleet_health.entries,
                bytes_per_entry=512.0,
            ))
            if self.load_tracker is not None:
                accountant.register(Meter(
                    STRUCT_LOAD, self.load_tracker.entries,
                    bytes_per_entry=256.0,
                ))
            if self.antientropy is not None:
                accountant.register(Meter(
                    STRUCT_ANTIENTROPY, self.antientropy.entries,
                    bytes_per_entry=256.0,
                ))

            def _transfer_entries() -> int:
                client = self.transfer_client or _peek_transfer_client()
                return client.entries() if client is not None else 0

            accountant.register(Meter(
                STRUCT_TRANSFER_PEERS, _transfer_entries,
                bytes_per_entry=4096.0,
            ))

            self.resource_accountant = accountant
            self.resourcegov = ResourceGovernor(
                accountant,
                ResourceGovConfig(
                    budget_mb=float(
                        env.get("resourcegov_budget_mb", 256.0)
                    ),
                    cooldown_s=float(
                        env.get("resourcegov_cooldown_s", 10.0)
                    ),
                    rss_probe=bool(
                        env.get("resourcegov_rss_probe", False)
                    ),
                ),
            )
            # Under governance, a stale quarantine reaps like an
            # explicit leave (same fan-out, same idempotent hooks).
            self.fleet_health.on_departed = self.reaper.reap
            if self.autopilot_registry is not None:
                self.resourcegov.register_knobs(self.autopilot_registry)
            if self.autopilot_signals is not None:
                self.autopilot_signals.resourcegov = self.resourcegov

    def start(self, with_subscriber: bool = True) -> None:
        self.indexer.run()
        self.event_pool.start(with_subscriber=with_subscriber)
        self._started = True

    def stop(self) -> None:
        self.event_pool.shutdown()
        self.indexer.shutdown()

    # -- admission plumbing --------------------------------------------------

    @staticmethod
    def _deadline_budget(request: web.Request):
        """Client-propagated deadline: the `X-Request-Deadline-Ms` header
        carries the caller's REMAINING budget in milliseconds (the HTTP
        sibling of the gRPC context deadline). Absent/garbled = no
        deadline."""
        raw = request.headers.get("X-Request-Deadline-Ms")
        if raw is None:
            return None
        try:
            return max(0.0, float(raw) / 1e3)
        except ValueError:
            return None

    @staticmethod
    def _shed_response(e: AdmissionRejected) -> web.Response:
        """429 + Retry-After: the explicit, bounded overload answer."""
        return web.json_response(
            {
                "error": str(e),
                "shed": e.kind,
                "retry_after_s": e.retry_after_s,
            },
            status=429,
            headers={"Retry-After": str(max(1, round(e.retry_after_s)))},
        )

    async def _admitted(self, request: web.Request, fn):
        """Run sync scoring work on a worker thread under the admission
        gate (when one is configured), with the client's deadline budget
        capping the queue wait. Raises `AdmissionRejected` on shed.

        Cross-process tracing seam: an `X-Kvtpu-Trace` header (or a W3C
        `traceparent` from an upstream gateway) makes the read path's
        root trace adopt the caller's trace id. Missing, truncated, or
        malformed values NEVER fail the request — they fall back to a
        fresh local trace, counted in
        kvcache_trace_carrier_errors_total."""
        carrier = request.headers.get(obs.HTTP_TRACE_HEADER)
        if carrier is None:
            carrier = request.headers.get("traceparent")

        def traced():
            if carrier is None:
                return fn()
            with obs.adopt(carrier):
                return fn()

        if self.admission is None:
            return await asyncio.to_thread(traced)
        budget = self._deadline_budget(request)
        admission = self.admission

        def gated():
            with admission.admit(budget):
                return traced()

        return await asyncio.to_thread(gated)

    # -- handlers ------------------------------------------------------------

    async def handle_score_completions(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
            prompt = body["prompt"]
            model = body["model"]
        except (json.JSONDecodeError, KeyError, TypeError) as e:
            return web.json_response(
                {"error": f"invalid request: {e}"}, status=400
            )
        pods = body.get("pods", [])
        lora_id = body.get("lora_id")
        try:
            scores = await self._admitted(
                request,
                lambda: self.indexer.get_pod_scores(
                    prompt, model, pods, lora_id=lora_id
                ),
            )
        except AdmissionRejected as e:
            return self._shed_response(e)
        except Exception as e:  # noqa: BLE001
            return web.json_response({"error": str(e)}, status=500)
        return web.json_response({"podScores": scores})

    async def handle_score_completions_batch(
        self, request: web.Request
    ) -> web.Response:
        """Bulk scoring: the whole batch runs through `score_many` — one
        amortized read-path pass, per-item results bit-identical to N
        sequential /score_completions calls. Per-item overload
        degradation applies (a shed item scores empty, the batch
        survives)."""
        from llm_d_kv_cache_manager_tpu.kvcache.indexer import ScoreRequest

        try:
            body = await request.json()
            raw = body["requests"]
            if not isinstance(raw, list):
                raise TypeError("requests must be a list")
            score_requests = [
                ScoreRequest(
                    prompt=item["prompt"],
                    model_name=item["model"],
                    pod_identifiers=item.get("pods", []),
                    lora_id=item.get("lora_id"),
                )
                for item in raw
            ]
        except (json.JSONDecodeError, KeyError, TypeError) as e:
            return web.json_response(
                {"error": f"invalid request: {e}"}, status=400
            )
        max_batch = int(self.env.get("score_batch_max", 128))
        if len(score_requests) > max_batch:
            return web.json_response(
                {"error": f"batch of {len(score_requests)} exceeds "
                          f"SCORE_BATCH_MAX={max_batch}"},
                status=400,
            )
        try:
            results = await self._admitted(
                request, lambda: self.indexer.score_many(score_requests)
            )
        except AdmissionRejected as e:
            return self._shed_response(e)
        except Exception as e:  # noqa: BLE001
            return web.json_response({"error": str(e)}, status=500)
        return web.json_response(
            {"results": [{"podScores": r.scores} for r in results]}
        )

    async def handle_score_chat_completions(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
            model = body["model"]
            render_request = RenderRequest.from_dict(body)
        except (json.JSONDecodeError, KeyError, TypeError) as e:
            return web.json_response({"error": f"invalid request: {e}"}, status=400)
        try:
            rendered = await asyncio.to_thread(self.templating.render, render_request)
            scores = await self._admitted(
                request,
                lambda: self.indexer.get_pod_scores(
                    rendered,
                    model,
                    body.get("pods", []),
                    lora_id=body.get("lora_id"),
                ),
            )
        except AdmissionRejected as e:
            return self._shed_response(e)
        except Exception as e:  # noqa: BLE001
            return web.json_response({"error": str(e)}, status=500)
        return web.json_response(
            {"podScores": scores, "templated_messages": rendered}
        )

    async def handle_debug_traces(self, request: web.Request) -> web.Response:
        """Flight-recorder dump: recent complete traces + slow outliers.

        Query filters (AND-combined): `n`/`limit` caps the recent list,
        `plane=` keeps traces whose root lives in that plane, `min_ms=`
        keeps traces at least that slow, `trace_id=` (16 hex) fetches one
        distributed trace exactly, `crit=1` attaches each trace's
        critical-path breakdown. The ring is snapshotted under the lock
        once; filtering and JSON rendering happen outside it."""
        q = request.query
        n = None
        raw_n = q.get("limit", q.get("n"))
        if raw_n is not None:
            try:
                n = max(0, int(raw_n))
            except ValueError:
                return web.json_response(
                    {"error": "limit must be an integer"}, status=400
                )
        min_ms = None
        if "min_ms" in q:
            try:
                min_ms = float(q["min_ms"])
            except ValueError:
                return web.json_response(
                    {"error": "min_ms must be a number"}, status=400
                )
        plane = q.get("plane")
        if plane is not None and plane not in obs.PLANES:
            return web.json_response(
                {"error": f"plane must be one of {list(obs.PLANES)}"},
                status=400,
            )
        snapshot = await asyncio.to_thread(
            lambda: obs.get_recorder().snapshot(
                n=n, plane=plane, min_ms=min_ms,
                trace_id=q.get("trace_id"),
                include_critical=q.get("crit") == "1",
            )
        )
        return web.json_response(snapshot)

    async def handle_debug_critical_path(
        self, request: web.Request
    ) -> web.Response:
        """Critical-path window summary: per-(span, hop) self-time along
        the longest dependency chain, aggregated over the recorder's
        recent ring and grouped by root name — "which hop do I optimize
        next", as one document. `root=` filters to one root name."""
        root = request.query.get("root")

        def build():
            traces = obs.get_recorder().recent()
            if root is not None:
                traces = [t for t in traces if t.name == root]
            return {
                "traces": len(traces),
                "roots": obs.aggregate_critical_path(traces),
            }

        return web.json_response(await asyncio.to_thread(build))

    async def handle_slo_status(self, request: web.Request) -> web.Response:
        """SLO burn-rate evaluation over the live registry (obs/slo.py):
        per-objective fast/slow-window burn rates and breach status."""
        if self.slo is None:
            return web.json_response(
                {"error": "slo monitoring disabled (set SLO=1)"}, status=400
            )
        return web.json_response(await asyncio.to_thread(self.slo.evaluate))

    async def handle_score_explain(self, request: web.Request) -> web.Response:
        """Scores with the decision evidence attached. Same pipeline as
        /score_completions (bit-identical scores); GET query params or the
        same JSON body as the scoring endpoint."""
        if request.method == "POST":
            try:
                body = await request.json()
                prompt = body["prompt"]
                model = body["model"]
            except (json.JSONDecodeError, KeyError, TypeError) as e:
                return web.json_response(
                    {"error": f"invalid request: {e}"}, status=400
                )
            pods = body.get("pods", [])
            lora_id = body.get("lora_id")
        else:
            prompt = request.query.get("prompt")
            model = request.query.get("model")
            if prompt is None or model is None:
                return web.json_response(
                    {"error": "prompt and model query params are required"},
                    status=400,
                )
            pods = [
                p for p in request.query.get("pods", "").split(",") if p
            ]
            lora_id = request.query.get("lora_id")
            if lora_id is not None:
                try:
                    lora_id = int(lora_id)
                except ValueError:
                    return web.json_response(
                        {"error": "lora_id must be an integer"}, status=400
                    )
        try:
            explain = await asyncio.to_thread(
                self.indexer.explain_scores, prompt, model, pods,
                lora_id=lora_id,
            )
        except Exception as e:  # noqa: BLE001
            return web.json_response({"error": str(e)}, status=500)
        # Which engine produced these scores (C arena vs pure Python) and
        # the running fallback count — evidence for "why was this slow".
        explain["native_core"] = self._native_core_section()
        return web.json_response(explain)

    async def handle_metrics(self, request: web.Request) -> web.Response:
        from prometheus_client import REGISTRY, generate_latest

        return web.Response(
            body=generate_latest(REGISTRY), content_type="text/plain"
        )

    async def handle_health(self, request: web.Request) -> web.Response:
        # Liveness ONLY: the process is up and serving HTTP. Whether the
        # event plane works is a readiness question — see /readyz — so a
        # restart loop is never triggered by a peer's outage.
        return web.json_response({"status": "ok"})

    def readiness(self) -> dict:
        """Readiness snapshot: event-plane progress + per-pod health."""
        subscriber = self.event_pool._subscriber  # noqa: SLF001
        sub_info = None
        sub_ready = True  # pools started without a subscriber (embedded
        # mode / direct event sinks) are ready by construction
        if subscriber is not None:
            failures = subscriber.consecutive_failures
            sub_info = {
                "thread_alive": subscriber.is_alive(),
                "consecutive_failures": failures,
                "endpoint": self.env.get("zmq_endpoint"),
            }
            sub_ready = subscriber.is_alive() and failures == 0
        workers = self.event_pool.workers_alive()
        pool_info = {
            "workers_alive": workers,
            "queue_depths": self.event_pool.queue_depths(),
            "dropped_events": self.event_pool.dropped_events,
            "removals_lost": self.event_pool.removals_lost,
        }
        ready = bool(self._started and workers > 0 and sub_ready)
        status = "ready" if ready else "unready"
        replication = None
        if self.replica is not None:
            replication = self.replica.readiness()
            if ready and replication["state"] != "ready":
                # Replaying the seq tail after a snapshot load: the view is
                # partially stale, so routers must not scatter-gather here
                # yet — but this is warm-up, not failure, and gets its own
                # status string (still 503, like unready).
                status = replication["state"]
        memo = self.indexer.token_processor.chain_memo
        return {
            "status": status,
            "started": self._started,
            # Replicated-control-plane section: replica id/partition shape,
            # readiness state (ready | replaying), snapshot age, replay
            # bookkeeping. None on monolithic deployments.
            "replication": replication,
            "subscriber": sub_info,
            "event_pool": pool_info,
            "fleet": self.fleet_health.summary(),
            # Read-path derivation cache effectiveness (observability only —
            # never gates readiness: a cold memo is a correct memo).
            "chain_memo": memo.stats() if memo is not None else None,
            # Flight-recorder health (ring occupancy, dropped traces,
            # slowest recent stage): degraded observability is itself
            # observable, but never gates readiness.
            "obs": obs.get_recorder().stats(),
            # SLO burn-rate evaluation (obs/slo.py): breach status per
            # objective. NEVER gates readiness — a breaching service is
            # degrading, not down; taking it out of rotation would turn
            # an alert into an outage.
            "slo": self.slo.evaluate() if self.slo is not None else None,
            # Admission gate occupancy + shed counters: a service AT
            # capacity and shedding correctly is still ready (shedding is
            # the designed overload behavior, not a failure).
            "admission": (
                self.admission.status() if self.admission is not None
                else None
            ),
            # Federation section: per-region digest age + staleness state,
            # the stale set, and failover counters. Peer-region staleness
            # never gates THIS region's readiness — a region serving its
            # own traffic while the WAN is dark is degraded, not down.
            "federation": (
                self.federation.status() if self.federation is not None
                else None
            ),
            # Anticipatory-prefetch section: session-table occupancy +
            # misprediction counters, and — when a prefetch plane is
            # wired — the queue's depth and PER-SOURCE drop counters, so
            # a budget-bounded prediction drop is distinguishable from a
            # route-prefetch drop. Never gates readiness: a cold (or
            # absent) predictor is a correct predictor.
            "prediction": self._prediction_section(),
            # Data-plane health: per-peer breaker state + consecutive
            # failures + EWMA fetch latency, and the hedge/corrupt/
            # oversized counters (previously a single opaque failure
            # counter). Never gates readiness — an open breaker means a
            # PEER is dark; this process degrades those fetches to misses
            # and keeps serving.
            "transfer": self._transfer_section(),
            # Index anti-entropy: per-pod advertised-vs-verified accuracy
            # EWMA + demotion factor, last audit time, and the purge/
            # readmit counters. Never gates readiness — a divergent POD
            # is being demoted and repaired; this process is fine.
            "index_health": self._index_health_section(),
            # SLO autopilot: knob positions vs baseline, rule states, and
            # the recent actuation tail. The /readyz poll is also one of
            # the controller's tick cadences (rate-limited internally).
            # NEVER gates readiness — an actuating autopilot is relieving
            # a burn, not failing.
            "autopilot": self._autopilot_section(),
            # Native scoring core: whether the C arena backs the read
            # path, its occupancy (keys/bytes/epoch + digest counters),
            # and how many batches fell back to the pure-Python path.
            # Never gates readiness — the fallback path is bit-identical,
            # just slower.
            "native_core": self._native_core_section(),
            # Resource governor: accounted bytes per structure, pressure
            # level, shed ladder + actuation journal, reaper stats. The
            # /readyz poll is one of the governor's tick cadences (rate-
            # limited internally). NEVER gates readiness — even critical
            # pressure means the process is shedding re-derivable caches
            # to keep serving: degraded, but ready by construction.
            "resource": self._resource_section(),
        }

    def _native_core_section(self) -> dict:
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.native_index import (
            NativeScoringIndex,
            fallback_total,
            have_native_index,
        )

        inner = getattr(
            self.indexer.kv_block_index, "inner", self.indexer.kv_block_index
        )
        if isinstance(inner, NativeScoringIndex):
            section = inner.native_status()
        else:
            section = {
                "enabled": False,
                "module_available": have_native_index(),
            }
        section["fallbacks"] = fallback_total()
        return section

    def _autopilot_section(self) -> Optional[dict]:
        if self.autopilot is None:
            return None
        self.autopilot.tick()
        return self.autopilot.status()

    def _resource_section(self) -> Optional[dict]:
        if self.resourcegov is None:
            # No governor: still surface the reaper (it runs either way)
            # once it has fanned out at least one departure.
            if self.reaper.stats_counters["reaps"]:
                return {"reaper": self.reaper.status()}
            return None
        self.resourcegov.tick()
        section = self.resourcegov.status()
        section["reaper"] = self.reaper.status()
        return section

    def _index_health_section(self) -> Optional[dict]:
        if self.antientropy is None:
            return None
        section = self.antientropy.status()
        if self.auditor is not None:
            section["auditor"] = self.auditor.status()
        if self.fetch_feedback is not None:
            section["fetch_feedback"] = self.fetch_feedback.status()
        return section

    def _transfer_section(self) -> Optional[dict]:
        from llm_d_kv_cache_manager_tpu.kv_connectors import (
            connector as conn_mod,
        )

        client = self.transfer_client or conn_mod.peek_default_client()
        if client is None:
            return None
        return client.status()

    def _prediction_section(self) -> Optional[dict]:
        if self.session_table is None and self.route_prefetcher is None:
            return None
        section: dict = {}
        if self.session_table is not None:
            stats = self.session_table.stats()
            metrics_collector.set_prediction_sessions(
                stats["tracked_sessions"]
            )
            section["table"] = stats
        if self.prefetch_scheduler is not None:
            section["scheduler"] = dict(self.prefetch_scheduler.stats)
        if self.route_prefetcher is not None:
            section["prefetcher"] = self.route_prefetcher.status()
        return section

    async def handle_readyz(self, request: web.Request) -> web.Response:
        payload = await asyncio.to_thread(self.readiness)
        status = 200 if payload["status"] == "ready" else 503
        return web.json_response(payload, status=status)

    async def handle_cluster_status(self, request: web.Request) -> web.Response:
        """Replication introspection: this replica's partition/readiness
        plus the scatter-gather front's per-replica health when one is
        wired. Same document the gRPC ClusterStatus method serves."""
        def build():
            return {
                "replica": (
                    self.replica.readiness() if self.replica is not None else None
                ),
                "scorer": (
                    self.cluster_scorer.status()
                    if self.cluster_scorer is not None
                    else None
                ),
            }

        return web.json_response(await asyncio.to_thread(build))

    async def handle_prediction_status(
        self, request: web.Request
    ) -> web.Response:
        """Anticipatory-prefetch introspection: the session table's
        occupancy/ETA evidence (soonest-expected sessions, tails as hex —
        data, never metric labels), misprediction counters, and the
        scheduler/prefetch-plane stats when an embedder wired them."""
        if self.session_table is None:
            return web.json_response(
                {"error": "prediction disabled (set PREDICTION=1)"},
                status=400,
            )

        def build():
            section = self._prediction_section() or {}
            section["soonest_sessions"] = self.session_table.snapshot()
            return section

        return web.json_response(await asyncio.to_thread(build))

    async def handle_antientropy_status(
        self, request: web.Request
    ) -> web.Response:
        """Anti-entropy introspection: the same document the /readyz
        `index_health` section embeds (per-pod trust evidence, auditor
        and fetch-feedback stats when an embedder wired them)."""
        if self.antientropy is None:
            return web.json_response(
                {"error": "anti-entropy disabled (set ANTIENTROPY=1)"},
                status=400,
            )
        return web.json_response(
            await asyncio.to_thread(self._index_health_section)
        )

    async def handle_autopilot_status(
        self, request: web.Request
    ) -> web.Response:
        """Autopilot introspection: one controller tick (rate-limited
        internally — fast polls are pure reads), then the status
        document the /readyz `autopilot` section embeds (knob positions
        vs baseline, rule firing evidence, recent actuation tail)."""
        if self.autopilot is None:
            return web.json_response(
                {"error": "autopilot disabled (set AUTOPILOT=1)"},
                status=400,
            )
        return web.json_response(
            await asyncio.to_thread(self._autopilot_section)
        )

    async def handle_resource_status(
        self, request: web.Request
    ) -> web.Response:
        """Resource-governor introspection: one governor tick (rate-
        limited internally), then the status document the /readyz
        `resource` section embeds (per-structure meters, pressure level,
        shed ladder, actuation journal, reaper stats). Critical pressure
        is a degraded-but-ready condition — this endpoint never serves
        503 on its own."""
        if self.resourcegov is None:
            return web.json_response(
                {
                    "error": "resource governor disabled "
                             "(set RESOURCEGOV=1)",
                    "reaper": self.reaper.status(),
                },
                status=400,
            )
        return web.json_response(
            await asyncio.to_thread(self._resource_section)
        )

    async def handle_placement_status(self, request: web.Request) -> web.Response:
        """Placement introspection: tracker occupancy/ingest counters, the
        currently-hot chains (heads as hex — data, never metric labels),
        and the replicator's policy stats when one is wired."""
        if self.popularity is None:
            return web.json_response(
                {"error": "placement disabled (set PLACEMENT=1)"},
                status=400,
            )

        def build():
            threshold = float(self.env.get("placement_hotness", 30.0))
            hot = self.popularity.hot_chains(threshold)
            metrics_collector.set_placement_hot_chains(len(hot))
            return {
                "tracker": self.popularity.stats(),
                "hotness_threshold": threshold,
                "hot_chains": [
                    {
                        "head": f"{c.head:016x}",
                        "score": round(c.score, 2),
                        "tenant_extra": list(c.extra),
                        "model": c.model_name,
                        "prefix_blocks": len(c.prefix_hashes),
                        "observations": c.observations,
                    }
                    for c in hot[:32]
                ],
                "replicator": (
                    self.replicator.status()
                    if self.replicator is not None else None
                ),
            }

        return web.json_response(await asyncio.to_thread(build))

    async def handle_pod_load(self, request: web.Request) -> web.Response:
        """POST: one pod-load report (the lightweight reporter seam —
        pods, or a sidecar scraping them, push their own queue depth /
        inflight / busy horizon here; the kvevents stream feeds the
        preemption-pressure signal independently). 400 when no load
        tracker is wired (ROUTING_POLICY=prefix_only needs no signals)."""
        if self.load_tracker is None:
            return web.json_response(
                {"error": "no load tracker (set ROUTING_POLICY=load_blend)"},
                status=400,
            )
        try:
            body = await request.json()
            pod = body["pod"]
            queue_depth = float(body.get("queue_depth", 0.0))
            inflight = float(body.get("inflight", 0.0))
            busy_s = body.get("busy_s")
        except (json.JSONDecodeError, KeyError, TypeError, ValueError) as e:
            return web.json_response(
                {"error": f"invalid request: {e}"}, status=400
            )
        busy_until = None
        if busy_s is not None:
            busy_until = self.load_tracker.clock() + max(0.0, float(busy_s))
        self.load_tracker.report(
            pod, queue_depth=queue_depth, inflight=inflight,
            busy_until=busy_until,
        )
        return web.json_response({"status": "ok"})

    async def handle_routing_status(self, request: web.Request) -> web.Response:
        """Saturation-resilience introspection: routing policy config +
        override stats + per-pod load snapshot, and the admission gate's
        depth/shed counters."""
        def build():
            return {
                "routing_policy": (
                    self.routing_policy.status()
                    if self.routing_policy is not None
                    else {"policy": "prefix_only"}
                ),
                "admission": (
                    self.admission.status()
                    if self.admission is not None else None
                ),
            }

        return web.json_response(await asyncio.to_thread(build))

    def _federation_disabled(self) -> Optional[web.Response]:
        if self.federation is None:
            return web.json_response(
                {"error": "federation disabled (set FEDERATION=1)"},
                status=400,
            )
        return None

    async def handle_federation_status(
        self, request: web.Request
    ) -> web.Response:
        """Federation introspection: per-region digest age/staleness,
        stale set, route/failover/digest counters (the same document the
        /readyz `federation` section embeds)."""
        err = self._federation_disabled()
        if err is not None:
            return err
        return web.json_response(
            await asyncio.to_thread(self.federation.status)
        )

    async def handle_federation_score(
        self, request: web.Request
    ) -> web.Response:
        """Two-level scoring entry: pick a region over the shipped
        digests, delegate precisely, return the pod scores WITH the
        region decision evidence. Body (POST) or query params (GET) are
        the /score_completions shape plus optional `home_region`."""
        err = self._federation_disabled()
        if err is not None:
            return err
        if request.method == "POST":
            try:
                body = await request.json()
                prompt = body["prompt"]
                model = body["model"]
            except (json.JSONDecodeError, KeyError, TypeError) as e:
                return web.json_response(
                    {"error": f"invalid request: {e}"}, status=400
                )
            pods = body.get("pods", [])
            lora_id = body.get("lora_id")
            home_region = body.get("home_region")
        else:
            prompt = request.query.get("prompt")
            model = request.query.get("model")
            if prompt is None or model is None:
                return web.json_response(
                    {"error": "prompt and model query params are required"},
                    status=400,
                )
            pods = [
                p for p in request.query.get("pods", "").split(",") if p
            ]
            lora_id = request.query.get("lora_id")
            if lora_id is not None:
                try:
                    lora_id = int(lora_id)
                except ValueError:
                    return web.json_response(
                        {"error": "lora_id must be an integer"}, status=400
                    )
            home_region = request.query.get("home_region")
        try:
            result = await self._admitted(
                request,
                lambda: self.federation.score_ex(
                    prompt, model, pods, lora_id=lora_id,
                    home_region=home_region,
                ),
            )
        except AdmissionRejected as e:
            return self._shed_response(e)
        except Exception as e:  # noqa: BLE001
            return web.json_response({"error": str(e)}, status=500)
        return web.json_response({
            "podScores": result.pod_scores.scores,
            "region": result.region,
            "detail": result.detail,
        })

    async def handle_federation_digest(
        self, request: web.Request
    ) -> web.Response:
        """The digest shipping seam. GET: build + return this region's
        encoded RegionDigest (peers pull on their own cadence). POST:
        ingest a peer's encoded digest from the request body."""
        err = self._federation_disabled()
        if err is not None:
            return err
        if request.method == "GET":
            try:
                data = await asyncio.to_thread(
                    self.federation.build_local_digest
                )
            except ValueError as e:
                return web.json_response({"error": str(e)}, status=400)
            return web.Response(
                body=data, content_type="application/octet-stream"
            )
        data = await request.read()
        from llm_d_kv_cache_manager_tpu.federation import DigestFormatError

        try:
            digest = await asyncio.to_thread(
                self.federation.ingest_digest, data
            )
        except (DigestFormatError, ValueError) as e:
            return web.json_response({"error": str(e)}, status=400)
        return web.json_response({
            "status": "ok",
            "region": digest.region_id,
            "seq": digest.seq,
            "hot_chains": len(digest.hot_chains),
        })

    async def handle_cluster_snapshot(self, request: web.Request) -> web.Response:
        """POST: drain the event pool and write this replica's snapshot
        (view + seq watermarks) to the configured path."""
        if self.replica is None:
            return web.json_response(
                {"error": "not a replicated deployment (set CLUSTER_REPLICAS "
                          "/ CLUSTER_SNAPSHOT_PATH)"},
                status=400,
            )
        try:
            stats = await asyncio.to_thread(self.replica.take_snapshot)
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=400)
        except OSError as e:
            return web.json_response({"error": str(e)}, status=500)
        return web.json_response(stats)

    def make_app(self) -> web.Application:
        app = web.Application()
        app.router.add_post("/score_completions", self.handle_score_completions)
        app.router.add_post(
            "/score_completions/batch", self.handle_score_completions_batch
        )
        app.router.add_post(
            "/score_chat_completions", self.handle_score_chat_completions
        )
        app.router.add_get("/metrics", self.handle_metrics)
        app.router.add_get("/health", self.handle_health)
        app.router.add_get("/readyz", self.handle_readyz)
        app.router.add_get("/cluster/status", self.handle_cluster_status)
        app.router.add_get("/routing/status", self.handle_routing_status)
        app.router.add_post("/pod_load", self.handle_pod_load)
        app.router.add_get("/placement/status", self.handle_placement_status)
        app.router.add_get(
            "/antientropy/status", self.handle_antientropy_status
        )
        app.router.add_get("/prediction/status", self.handle_prediction_status)
        app.router.add_get(
            "/federation/status", self.handle_federation_status
        )
        app.router.add_get("/federation/score", self.handle_federation_score)
        app.router.add_post(
            "/federation/score", self.handle_federation_score
        )
        app.router.add_get(
            "/federation/digest", self.handle_federation_digest
        )
        app.router.add_post(
            "/federation/digest", self.handle_federation_digest
        )
        app.router.add_post("/cluster/snapshot", self.handle_cluster_snapshot)
        app.router.add_get("/slo/status", self.handle_slo_status)
        app.router.add_get("/autopilot/status", self.handle_autopilot_status)
        app.router.add_get("/resource/status", self.handle_resource_status)
        app.router.add_get(
            "/debug/critical_path", self.handle_debug_critical_path
        )
        app.router.add_get("/debug/traces", self.handle_debug_traces)
        app.router.add_get("/debug/score_explain", self.handle_score_explain)
        app.router.add_post("/debug/score_explain", self.handle_score_explain)
        return app


def main() -> None:
    kvlog.setup()
    env = config_from_env()
    service = ScoringService(env)
    service.start()
    try:
        web.run_app(service.make_app(), port=env["http_port"])
    finally:
        service.stop()


if __name__ == "__main__":
    main()

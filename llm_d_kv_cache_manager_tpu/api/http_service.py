"""HTTP scoring service — the shipped container entrypoint.

Parity target: /root/reference/examples/kv_events/online/main.go (the
reference's Dockerfile entrypoint): one process wiring the Indexer read path,
the ZMQ KVEvents write plane, and Prometheus metrics behind HTTP:

  POST /score_completions       {"prompt", "model", "pods"?} -> {"podScores"}
  POST /score_chat_completions  {"messages"/"conversations", "model",
                                 "chat_template"?, "pods"?}
                                -> {"podScores", "templated_messages"}
  GET  /metrics                 Prometheus exposition
  GET  /health                  liveness

Env config mirrors the reference's variable set (online/main.go:41-58):
ZMQ_ENDPOINT, ZMQ_TOPIC, POOL_CONCURRENCY, PYTHONHASHSEED (hash seed!),
BLOCK_SIZE, BLOCK_HASH_ALGO, HTTP_PORT, HF_TOKEN, LOCAL_TOKENIZER_DIR.

Run: python -m llm_d_kv_cache_manager_tpu.api.http_service
"""

from __future__ import annotations

import asyncio
import json
import os
from typing import Optional

from aiohttp import web

from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import IndexConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import EventPool, EventPoolConfig
from llm_d_kv_cache_manager_tpu.preprocessing.chat_completions import (
    ChatTemplatingProcessor,
    RenderRequest,
)
from llm_d_kv_cache_manager_tpu.tokenization.pool import TokenizersPoolConfig
from llm_d_kv_cache_manager_tpu.utils import logging as kvlog

logger = kvlog.get_logger("api.http")


def config_from_env() -> dict:
    return {
        "zmq_endpoint": os.environ.get("ZMQ_ENDPOINT", "tcp://*:5557"),
        "zmq_topic": os.environ.get("ZMQ_TOPIC", "kv@"),
        "pool_concurrency": int(os.environ.get("POOL_CONCURRENCY", "4")),
        "hash_seed": os.environ.get("PYTHONHASHSEED", ""),
        # "fnv64_cbor" (reference parity) or "sha256_cbor_64bit" (bit-exact
        # with vLLM --prefix-caching-hash-algo=sha256_cbor_64bit fleets).
        "hash_algo": os.environ.get("BLOCK_HASH_ALGO", "fnv64_cbor"),
        "block_size": int(os.environ.get("BLOCK_SIZE", "16")),
        "http_port": int(os.environ.get("HTTP_PORT", "8080")),
        "hf_token": os.environ.get("HF_TOKEN"),
        "enable_hf": os.environ.get("ENABLE_HF_TOKENIZER", "") == "1",
        "enable_metrics": os.environ.get("ENABLE_METRICS", "1") == "1",
        # Shared index backend (redis:// or valkey:// URL) for multi-replica
        # managers; empty -> in-memory index.
        "index_url": os.environ.get("INDEX_URL", ""),
        # UDS tokenizer sidecar socket; empty -> local tokenization only.
        "uds_socket": os.environ.get("UDS_SOCKET", ""),
    }


class ScoringService:
    """Owns the Indexer (read path) + EventPool (write plane)."""

    def __init__(self, env: Optional[dict] = None, indexer: Optional[Indexer] = None):
        env = env or config_from_env()
        self.env = env
        self.templating = ChatTemplatingProcessor()

        if indexer is not None:  # injected (tests / embedding)
            self.indexer = indexer
        else:
            index_config = IndexConfig.default()
            if env.get("index_url"):
                from llm_d_kv_cache_manager_tpu.kvcache.kvblock.redis_index import (
                    RedisIndexConfig,
                )

                index_config = IndexConfig(
                    redis_config=RedisIndexConfig(url=env["index_url"])
                )
            indexer_config = IndexerConfig(
                token_processor_config=TokenProcessorConfig(
                    block_size=env["block_size"],
                    hash_seed=env["hash_seed"],
                    hash_algo=env.get("hash_algo", "fnv64_cbor"),
                ),
                kv_block_index_config=index_config,
                tokenizers_pool_config=TokenizersPoolConfig(
                    enable_local=True,
                    enable_uds=bool(env.get("uds_socket")),
                    uds_socket_path=env.get("uds_socket") or None,
                    enable_hf=env["enable_hf"],
                    hf_auth_token=env.get("hf_token"),
                ),
            )
            indexer_config.kv_block_index_config.enable_metrics = env["enable_metrics"]
            self.indexer = Indexer(
                config=indexer_config, chat_templating=self.templating
            )

        self.event_pool = EventPool(
            EventPoolConfig(
                zmq_endpoint=env["zmq_endpoint"],
                topic_filter=env["zmq_topic"],
                concurrency=env["pool_concurrency"],
            ),
            self.indexer.kv_block_index,
            self.indexer.token_processor,
        )

    def start(self, with_subscriber: bool = True) -> None:
        self.indexer.run()
        self.event_pool.start(with_subscriber=with_subscriber)

    def stop(self) -> None:
        self.event_pool.shutdown()
        self.indexer.shutdown()

    # -- handlers ------------------------------------------------------------

    async def handle_score_completions(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
            prompt = body["prompt"]
            model = body["model"]
        except (json.JSONDecodeError, KeyError, TypeError) as e:
            return web.json_response(
                {"error": f"invalid request: {e}"}, status=400
            )
        pods = body.get("pods", [])
        lora_id = body.get("lora_id")
        try:
            scores = await asyncio.to_thread(
                self.indexer.get_pod_scores, prompt, model, pods, lora_id=lora_id
            )
        except Exception as e:  # noqa: BLE001
            return web.json_response({"error": str(e)}, status=500)
        return web.json_response({"podScores": scores})

    async def handle_score_chat_completions(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
            model = body["model"]
            render_request = RenderRequest.from_dict(body)
        except (json.JSONDecodeError, KeyError, TypeError) as e:
            return web.json_response({"error": f"invalid request: {e}"}, status=400)
        try:
            rendered = await asyncio.to_thread(self.templating.render, render_request)
            scores = await asyncio.to_thread(
                self.indexer.get_pod_scores,
                rendered,
                model,
                body.get("pods", []),
                lora_id=body.get("lora_id"),
            )
        except Exception as e:  # noqa: BLE001
            return web.json_response({"error": str(e)}, status=500)
        return web.json_response(
            {"podScores": scores, "templated_messages": rendered}
        )

    async def handle_metrics(self, request: web.Request) -> web.Response:
        from prometheus_client import REGISTRY, generate_latest

        return web.Response(
            body=generate_latest(REGISTRY), content_type="text/plain"
        )

    async def handle_health(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "ok"})

    def make_app(self) -> web.Application:
        app = web.Application()
        app.router.add_post("/score_completions", self.handle_score_completions)
        app.router.add_post(
            "/score_chat_completions", self.handle_score_chat_completions
        )
        app.router.add_get("/metrics", self.handle_metrics)
        app.router.add_get("/health", self.handle_health)
        return app


def main() -> None:
    kvlog.setup()
    env = config_from_env()
    service = ScoringService(env)
    service.start()
    try:
        web.run_app(service.make_app(), port=env["http_port"])
    finally:
        service.stop()


if __name__ == "__main__":
    main()

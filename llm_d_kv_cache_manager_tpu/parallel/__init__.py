from llm_d_kv_cache_manager_tpu.parallel.mesh import (
    make_mesh,
    param_shardings,
    shard_params,
)
from llm_d_kv_cache_manager_tpu.parallel.ring_attention import ring_attention
from llm_d_kv_cache_manager_tpu.parallel.pipeline import pipeline_forward
from llm_d_kv_cache_manager_tpu.parallel.multihost import (
    initialize_distributed,
    make_hybrid_mesh,
)

__all__ = [
    "make_mesh",
    "param_shardings",
    "shard_params",
    "ring_attention",
    "pipeline_forward",
    "initialize_distributed",
    "make_hybrid_mesh",
]

"""Tensor-parallel sharding for the SERVING path (paged prefill/decode).

A vLLM-TPU pod runs one model replica tensor-parallel over the chips of its
slice; the pod is still ONE pod to the control plane (one pod identifier,
one event stream, one entry in the index). This module provides the
shardings that put the engine's serving state — weights and the paged KV
cache — on a tp mesh so the existing jitted serving ops (`prefill_cache`,
`decode_step_cache`, `verify_step_cache` in models/llama.py) compile to
SPMD programs with the canonical Megatron collectives over ICI.

Design (scaling-book recipe: pick a mesh, annotate shardings, let XLA
insert collectives):

- Mesh: 1-D ("tp",) over the slice's chips.
- Weights: the same Megatron specs the training path uses
  (parallel/mesh.param_specs — column-parallel wq/wk/wv/w_gate/w_up,
  row-parallel wo/w_down, vocab-parallel out). Per layer the forward
  reduces to two all-reduces (post-wo, post-w_down) riding ICI.
- KV pages: sharded over the KV-HEAD axis — every cache component is
  [n_layers, n_kv_heads, n_pages, page_size, ...] with heads at axis 1, so
  P(None, "tp", None, None, None) gives each chip its heads' pages for
  EVERY page id. The block table stays a host-side, replicated int32 array:
  page allocation (engine/block_manager.py) is tp-invariant, which is what
  keeps the control plane's one-index-entry-per-pod model valid — a block
  is resident on the pod iff every chip holds its head-shard of the page.
- Activations/tokens/tables/seq_lens: replicated (decode batches are tiny;
  GSPMD re-shards q/k/v onto heads right after the column-parallel
  projections).

tp must divide n_kv_heads (and n_q_heads): the head-major page layout
(ops/paged_attention.py) makes the kv-head axis the natural shard axis, the
same choice vLLM's TPU backend makes for its KV cache.

Reference anchor: the reference control plane never shards (its pods' TP=4
is invisible to it, /root/reference/benchmarking/37-capacity/README.md:5);
this module is the engine-side capability that makes a TP pod real in the
TPU build.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from llm_d_kv_cache_manager_tpu.parallel.mesh import shard_params


def tp_mesh(tp: int, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D ("tp",) mesh over the first `tp` devices (the pod's slice)."""
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < tp:
        raise ValueError(f"need {tp} devices for tp={tp}, have {len(devices)}")
    return Mesh(np.array(devices[:tp]), axis_names=("tp",))


def kv_cache_shardings(mesh: Mesh, n_components: int) -> Tuple[NamedSharding, ...]:
    """Shardings for a paged KV cache tuple — bf16 (k, v) or int8-quantized
    (k_q, k_scale, v_q, v_scale). Every component is laid out
    [n_layers, n_kv_heads, n_pages, page_size, ...]; shard the head axis."""
    spec = P(None, "tp", None, None, None)
    return tuple(NamedSharding(mesh, spec) for _ in range(n_components))


def shard_serving_params(params: dict, mesh: Mesh) -> dict:
    """Place model weights with the Megatron specs — the serving path uses
    the SAME shardings as training (parallel/mesh.py), so the two can never
    diverge; the mesh here is 1-D ("tp",) and the specs reference only tp."""
    return shard_params(params, mesh)


def shard_kv_cache(kv_cache: tuple, mesh: Mesh) -> tuple:
    """Place a paged KV cache (either format) head-sharded on the mesh."""
    return tuple(
        jax.device_put(c, s)
        for c, s in zip(kv_cache, kv_cache_shardings(mesh, len(kv_cache)))
    )


def validate_tp(tp: int, n_q_heads: int, n_kv_heads: int) -> None:
    if n_kv_heads % tp or n_q_heads % tp:
        raise ValueError(
            f"tp={tp} must divide n_kv_heads={n_kv_heads} and "
            f"n_q_heads={n_q_heads} (head-sharded KV pages)"
        )

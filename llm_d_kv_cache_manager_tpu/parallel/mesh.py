"""Device mesh + sharding rules for the flagship model.

TPU-first scaling: pick a mesh, annotate shardings, let XLA/GSPMD insert the
collectives over ICI. Axes:

- "dp": data parallel — batch dimension of activations.
- "tp": tensor parallel — attention heads and MLP hidden dimension
  (Megatron-style: column-parallel wq/wk/wv/w_gate/w_up, row-parallel
  wo/w_down, vocab-parallel output projection). With these specs the
  per-layer communication under jit reduces to the canonical two
  all-reduces (post-wo, post-w_down) riding ICI.
- "sp": sequence parallel for long context — handled separately by
  parallel.ring_attention (shard_map + ppermute), not by these specs.

The reference control plane has no in-framework parallelism (SURVEY.md §2.5);
this module exists for the engine side of the TPU build.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    dp: int = 1,
    tp: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (dp, tp) mesh from the first dp*tp available devices."""
    devices = list(devices if devices is not None else jax.devices())
    need = dp * tp
    if len(devices) < need:
        raise ValueError(f"need {need} devices for dp={dp} x tp={tp}, have {len(devices)}")
    grid = np.array(devices[:need]).reshape(dp, tp)
    return Mesh(grid, axis_names=("dp", "tp"))


# PartitionSpecs for one decoder layer's stacked params ([n_layers, ...]).
_LAYER_SPECS: Dict[str, P] = {
    "attn_norm": P(None, None),
    "wq": P(None, None, "tp"),
    "wk": P(None, None, "tp"),
    "wv": P(None, None, "tp"),
    "wo": P(None, "tp", None),
    "mlp_norm": P(None, None),
    "w_gate": P(None, None, "tp"),
    "w_up": P(None, None, "tp"),
    "w_down": P(None, "tp", None),
}


def param_specs(attn_bias: bool = False) -> Dict:
    """PartitionSpec pytree matching models.llama.init_params structure.
    attn_bias adds the Qwen2-family bq/bk/bv rows: each bias lives on its
    projection's OUTPUT dim, so it shards the same "tp" axis as the
    column-parallel weight it adds onto ([n_layers, q_dim/kv_dim])."""
    layers = dict(_LAYER_SPECS)
    if attn_bias:
        layers.update({
            "bq": P(None, "tp"),
            "bk": P(None, "tp"),
            "bv": P(None, "tp"),
        })
    return {
        "embed": P(None, None),  # replicated; activations gather from it
        "layers": layers,
        "final_norm": P(None),
        "out": P(None, "tp"),  # vocab-parallel logits
    }


def param_shardings(mesh: Mesh, attn_bias: bool = False) -> Dict:
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec),
        param_specs(attn_bias),
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_params(params: Dict, mesh: Mesh) -> Dict:
    """Place a host-resident param pytree onto the mesh. The bias rows'
    presence is read off the pytree itself so callers never pass a flag
    the params already encode."""
    shardings = param_shardings(mesh, attn_bias="bq" in params["layers"])
    return jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, s), params, shardings
    )


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P("dp", None))

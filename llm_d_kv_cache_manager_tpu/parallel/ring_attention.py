"""Ring attention: exact causal attention over sequence-sharded inputs.

Long-context support for the engine side of the TPU build: the sequence is
sharded across an "sp" mesh axis; each device holds a local Q/K/V chunk and
K/V blocks rotate around the ring via `lax.ppermute` (ICI neighbor exchange)
while every device accumulates online-softmax partial results for its local
queries. After sp steps every query has attended to every key — exact
attention, O(L/sp) memory per device, communication overlapped by XLA.

Causality is enforced per (query-chunk, key-chunk) pair: a device at ring
position i fully attends chunks j < i, applies the triangular mask at j == i,
and skips j > i (their contribution is masked to -inf, preserving static
shapes for the compiler).

Use under shard_map, e.g.:

    mesh = Mesh(devices, ("sp",))
    attn = shard_map(
        functools.partial(ring_attention, axis_name="sp"),
        mesh=mesh,
        in_specs=(P(None, "sp", None, None),) * 3,
        out_specs=P(None, "sp", None, None),
    )
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def ring_attention(
    q: jax.Array,  # [B, L_local, n_heads, head_dim]
    k: jax.Array,  # [B, L_local, n_heads, head_dim]
    v: jax.Array,
    axis_name: str = "sp",
) -> jax.Array:
    """Exact causal attention with K/V rotating around the `axis_name` ring."""
    n_shards = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, l_local, n_heads, head_dim = q.shape
    scale = 1.0 / (head_dim**0.5)

    qf = jnp.swapaxes(q, 1, 2).astype(jnp.float32)  # [B, H, Lq, D]

    q_pos = jnp.arange(l_local)[:, None]  # local positions within a chunk
    k_pos = jnp.arange(l_local)[None, :]

    def step(carry, _):
        k_blk, v_blk, m, l, acc, src = carry
        # src = ring position the current K/V block originated from.
        kf = jnp.swapaxes(k_blk, 1, 2).astype(jnp.float32)  # [B, H, Lk, D]
        vf = jnp.swapaxes(v_blk, 1, 2).astype(jnp.float32)

        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale

        # Causal mask across chunks: full if src < my_idx, triangular if
        # equal, all-masked if src > my_idx.
        same = src == my_idx
        before = src < my_idx
        mask = jnp.where(same, k_pos <= q_pos, before)  # [Lq, Lk] bool
        s = jnp.where(mask[None, None], s, -jnp.inf)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # All-masked rows keep m = -inf; guard the exp against inf - inf.
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(s - m_safe)
        p = jnp.where(mask[None, None], p, 0.0)
        alpha = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_safe))
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum("bhqk,bhkd->bhqd", p, vf)

        # Rotate K/V to the next device on the ring.
        perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        src_next = (src - 1) % n_shards

        return (k_next, v_next, m_new, l_new, acc_new, src_next), None

    # Derive accumulators from qf so they carry the same device-varying type
    # as the rotating K/V blocks (shard_map manual-axes typing).
    m0 = jnp.full_like(qf[..., :1], -jnp.inf)
    l0 = jnp.zeros_like(qf[..., :1])
    acc0 = jnp.zeros_like(qf)

    carry, _ = jax.lax.scan(step, (k, v, m0, l0, acc0, my_idx), None, length=n_shards)
    _k, _v, _m, l_fin, acc, _src = carry

    out = acc / jnp.where(l_fin == 0, 1.0, l_fin)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)  # [B, L_local, H, D]

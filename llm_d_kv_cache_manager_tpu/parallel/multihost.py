"""Multi-host (multi-slice) mesh construction: ICI inside, DCN across.

The reference's distributed story is fleet-level (N independent engine pods
over ZMQ/Redis — SURVEY.md §2.6); the TPU build adds the device-level story:
scale one engine across hosts/slices with a hybrid mesh where the fast axes
(tp/sp) stay inside a slice riding ICI and the outer axis (dp, or pp stages)
crosses slices over DCN. XLA then places all-reduces per axis on the right
fabric automatically — the "How to Scale Your Model" recipe.

On a single process this degenerates gracefully (dcn axis size 1), so the
same code path runs everywhere; under a real multi-host launch call
`initialize_distributed()` first (one controller per host).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


_initialized = False


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    auto: bool = False,
) -> None:
    """Initialize jax.distributed (idempotent).

    Three modes:
    - explicit: pass coordinator_address (and peers) directly;
    - env: JAX_COORDINATOR_ADDRESS set by the launcher;
    - auto: `auto=True` or KVTPU_DISTRIBUTED_AUTO=1 calls the argument-less
      `jax.distributed.initialize()`, which auto-detects the coordinator
      from TPU pod metadata — the standard Cloud TPU multi-host recipe.
    With none of these, it is a single-host no-op.

    Must run before any JAX computation/backend use — so the guard is a
    module flag, NOT jax.process_count() (which would itself initialize the
    local backend and break the multi-host case this function exists for).
    """
    global _initialized
    if _initialized:
        return
    import os

    if coordinator_address is None:
        coordinator_address = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if coordinator_address is None:
        if auto or os.environ.get("KVTPU_DISTRIBUTED_AUTO") == "1":
            jax.distributed.initialize()  # TPU-metadata auto-detection
            _initialized = True
        return  # single-host run
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True


def make_hybrid_mesh(
    ici_axes: dict,
    dcn_axes: Optional[dict] = None,
) -> Mesh:
    """Build a mesh with `ici_axes` inside each slice and `dcn_axes` across
    slices/hosts, e.g. make_hybrid_mesh({"tp": 4, "sp": 2}, {"dp": 2}).

    Single-slice fallback: if only one host/slice is present, DCN axes of
    size 1 are still materialized so downstream PartitionSpecs work
    unchanged.
    """
    dcn_axes = dict(dcn_axes or {})
    ici_axes = dict(ici_axes)
    axis_names = tuple(dcn_axes) + tuple(ici_axes)
    shape = tuple(dcn_axes.values()) + tuple(ici_axes.values())
    n_needed = int(np.prod(shape)) if shape else 1

    devices = jax.devices()
    if len(devices) < n_needed:
        raise ValueError(
            f"hybrid mesh {dict(zip(axis_names, shape))} needs {n_needed} "
            f"devices, have {len(devices)}"
        )

    if jax.process_count() > 1 and dcn_axes:
        from jax.experimental import mesh_utils

        grid = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=tuple(ici_axes.values()),
            dcn_mesh_shape=tuple(dcn_axes.values()),
        )
        # create_hybrid_device_mesh returns shape dcn+ici already.
        return Mesh(grid, axis_names)

    grid = np.array(devices[:n_needed]).reshape(shape or (1,))
    return Mesh(grid, axis_names or ("dp",))

"""Pipeline parallelism: GPipe-style microbatched stages over a "pp" axis.

The flagship model's stacked layer params ([n_layers, ...]) are sharded on
their leading axis across the "pp" mesh dimension, so each device owns a
contiguous block of layers. Microbatches of embedded activations flow through
the stages: at every schedule tick each stage applies its local layers and
hands its activation to the next stage via `lax.ppermute` (one ICI hop —
neighbor-only traffic). The schedule is the classic n_micro + n_stages - 1
tick fill-and-drain; shapes are static, the tick loop is a Python loop over a
small constant, and XLA overlaps the permutes with compute.

Exactness: identical math to running all layers on one device — verified in
tests against models.llama.forward_dense.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from llm_d_kv_cache_manager_tpu.models.llama import (
    LlamaConfig,
    _dense_attention,
    _k_proj,
    _mlp,
    _qv_proj_with_lora,
    _rope,
    rms_norm,
)


def _apply_local_layers(config: LlamaConfig, layers: Dict, x: jax.Array) -> jax.Array:
    """Run this stage's layer slice. x: [mb, L, d] (already embedded)."""
    c = config
    mb, l, _ = x.shape
    positions = jnp.broadcast_to(jnp.arange(l), (mb, l))

    def layer_fn(x, layer):
        h = rms_norm(x, layer["attn_norm"], c.rms_eps)
        q_flat, v_flat = _qv_proj_with_lora(h, layer, None)
        q = q_flat.reshape(mb, l, c.n_q_heads, c.head_dim)
        k = _k_proj(layer, h).reshape(mb, l, c.n_kv_heads, c.head_dim)
        v = v_flat.reshape(mb, l, c.n_kv_heads, c.head_dim)
        q = _rope(q, positions, c.rope_theta)
        k = _rope(k, positions, c.rope_theta)
        attn = _dense_attention(q, k, v, 0, window=c.sliding_window)
        x = x + attn.reshape(mb, l, c.q_dim) @ layer["wo"]
        h = rms_norm(x, layer["mlp_norm"], c.rms_eps)
        x = x + _mlp(layer, h)
        return x, None

    x, _ = jax.lax.scan(layer_fn, x, layers)
    return x


def pipeline_forward(
    config: LlamaConfig,
    layer_params: Dict,
    x_embedded: jax.Array,  # [n_micro, mb, L, d]
    mesh: Mesh,
    axis: str = "pp",
) -> jax.Array:
    """Run the stacked layers as a pipeline over `axis`. Returns
    [n_micro, mb, L, d] final hidden states (before final norm/unembed)."""
    n_stages = mesh.shape[axis]
    n_micro = x_embedded.shape[0]

    def stage_body(layers, x_micro):
        idx = jax.lax.axis_index(axis)
        mb_shape = x_micro.shape[1:]
        state = jnp.zeros(mb_shape, x_micro.dtype)
        outputs = jnp.zeros((n_micro,) + mb_shape, x_micro.dtype)

        for t in range(n_micro + n_stages - 1):
            # Stage 0 injects microbatch t; other stages use what arrived.
            if t < n_micro:
                inject = x_micro[t]
                state = jnp.where(idx == 0, inject, state)
            # Compute only when this stage has a live microbatch: ticks
            # [idx, idx + n_micro). Predication keeps shapes static.
            active = jnp.logical_and(t >= idx, t < idx + n_micro)
            computed = _apply_local_layers(config, layers, state)
            state = jnp.where(active, computed, state)
            # Last stage records its finished microbatch.
            micro_idx = t - (n_stages - 1)
            is_last_and_done = jnp.logical_and(idx == n_stages - 1, active)
            if micro_idx >= 0:
                outputs = jnp.where(
                    is_last_and_done,
                    outputs.at[micro_idx].set(state),
                    outputs,
                )
            # Hand activations down the pipe (non-cyclic neighbor permute).
            if n_stages > 1:
                state = jax.lax.ppermute(
                    state, axis, [(i, i + 1) for i in range(n_stages - 1)]
                )
        return outputs[None]  # [1, n_micro, mb, L, d]

    in_specs = (
        jax.tree_util.tree_map(lambda _: P(axis), layer_params),
        P(),  # replicated microbatches
    )
    staged = jax.shard_map(
        stage_body,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=P(axis),
        check_vma=False,
    )(layer_params, x_embedded)
    return staged[-1]  # last stage's outputs

"""Indexer orchestrator: the read path.

Parity target: kvcache.Indexer (/root/reference/pkg/kvcache/indexer.go:62-166).
`get_pod_scores` runs the four-stage read path:

  1. tokenize the prompt (chat-template render → prefix-store shortcut →
     full tokenization) via the tokenization pool,
  2. convert tokens to chained KV-block keys (ChunkedTokenDatabase),
  3. look the keys up in the KV-block index (which pods hold which blocks),
  4. score pods by weighted longest consecutive cached prefix.

The write plane (kvevents.Pool) is constructed separately and shares this
Indexer's `kv_block_index` and token processor — index sharing is the only
read/write coupling, as in the reference
(/root/reference/examples/kv_events/online/main.go:115,248-258).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from llm_d_kv_cache_manager_tpu import obs
from llm_d_kv_cache_manager_tpu.kvcache.backend import (
    KVCacheBackendConfig,
    default_kv_cache_backend_configs,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import Index, IndexConfig, new_index
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    ChunkedTokenDatabase,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.scorer import (
    KVBlockScorerConfig,
    new_kv_block_scorer,
)
from llm_d_kv_cache_manager_tpu.tokenization.pool import (
    PoolOverloadedError,
    TokenizationPool,
    TokenizersPoolConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.prefixstore.indexer import (
    PrefixStoreConfig,
    new_prefix_store,
)
from llm_d_kv_cache_manager_tpu.utils import logging as kvlog

logger = kvlog.get_logger("kvcache.indexer")


@dataclass
class IndexerConfig:
    prefix_store_config: PrefixStoreConfig = field(default_factory=PrefixStoreConfig)
    token_processor_config: TokenProcessorConfig = field(
        default_factory=TokenProcessorConfig
    )
    kv_block_index_config: IndexConfig = field(default_factory=IndexConfig.default)
    scorer_config: KVBlockScorerConfig = field(default_factory=KVBlockScorerConfig)
    tokenizers_pool_config: TokenizersPoolConfig = field(
        default_factory=TokenizersPoolConfig
    )
    backend_configs: List[KVCacheBackendConfig] = field(
        default_factory=default_kv_cache_backend_configs
    )


@dataclass
class ScoreRequest:
    """One item of a `score_many` batch — the same argument set
    `get_pod_scores_ex` takes, carried as data so a router can hand the
    whole arrival window over in one call."""

    prompt: str
    model_name: str
    pod_identifiers: Sequence[str] = ()
    render_request: Optional[object] = None
    lora_id: Optional[object] = None


@dataclass
class PodScores:
    """Read-path result carrying the routing signal AND the transfer-plane
    signal. `scores` is what `get_pod_scores` always returned (post
    fleet-health filtering). `match_blocks` is each pod's matched-prefix
    length in blocks (pre-filter — a demoted pod's cache state is still
    real), and `block_hashes` is the prompt's chain in order, so the exact
    set of blocks any pod will MISS is `block_hashes[match_blocks[pod]:]` —
    known at routing time, and the input the route-driven prefetcher feeds
    the chosen pod before the engine faults on it."""

    scores: Dict[str, float] = field(default_factory=dict)
    match_blocks: Dict[str, int] = field(default_factory=dict)
    block_hashes: List[int] = field(default_factory=list)

    def missing_tail(self, pod_identifier: str) -> List[int]:
        """Chain hashes the pod does not hold as a leading prefix — what a
        router should hand the pod's prefetch queue when choosing it."""
        return self.block_hashes[self.match_blocks.get(pod_identifier, 0):]


class Indexer:
    """KV-cache-aware pod scorer over a fleet of vLLM-TPU pods."""

    def __init__(
        self,
        config: Optional[IndexerConfig] = None,
        tokenization_pool: Optional[TokenizationPool] = None,
        kv_block_index: Optional[Index] = None,
        chat_templating=None,
        fleet_health=None,
        popularity=None,
        routing_policy=None,
        prediction=None,
        antientropy=None,
    ):
        self.config = config or IndexerConfig()
        # Optional fleethealth.FleetHealthTracker: when wired, scores pass
        # through `filter_scores` — suspect pods demoted, stale pods
        # excluded (and their entries bulk-purged on detection). A healthy
        # fleet passes through untouched, so enabling the subsystem is
        # bit-identical on the no-fault path.
        self.fleet_health = fleet_health
        # Optional kvcache.routing.RoutingPolicy: the saturation-regime
        # load blend, applied AFTER fleet-health filtering (health decides
        # what is trustworthy; the policy decides what is affordable). The
        # prefix_only policy — and None, the default — return the scores
        # dict unchanged, pinning the pure-prefix path bit-identical.
        self.routing_policy = routing_policy
        # Optional antientropy.AntiEntropyTracker: truth-weighted score
        # demotion, applied between fleet-health filtering (is the pod's
        # STREAM alive?) and the routing policy (is the pod affordable?):
        # a pod whose advertised-vs-verified accuracy EWMA fell below the
        # distrust threshold has its prefix scores decayed like a suspect
        # pod's, recovering as audits come back clean. A clean (or absent)
        # tracker returns the scores dict unchanged — the SAME object —
        # so attachment is bit-identical on a truthful fleet (pinned by
        # tests/test_antientropy.py).
        self.antientropy = antientropy
        # Optional placement.ChainPopularityTracker: every scored request
        # reports its chain head + tenant/LoRA extra to the hot-prefix
        # detector (placement/popularity.py). Observation only — scores are
        # bit-identical with the tracker attached, and None (the default)
        # keeps the hot path at one attribute check.
        self.popularity = popularity
        # Optional prediction.SessionTable: every scored request reports
        # its chain + token slice to the session predictor
        # (prediction/sessions.py), which learns per-session next-turn
        # ETAs and continuation prefixes. Observation only — same contract
        # as the popularity seam: scores are bit-identical with a table
        # attached, None costs one attribute check.
        self.prediction = prediction

        self.prefix_store = (
            tokenization_pool.prefix_store
            if tokenization_pool is not None
            else new_prefix_store(self.config.prefix_store_config)
        )
        self.token_processor = ChunkedTokenDatabase(self.config.token_processor_config)
        self.kv_block_index = kv_block_index or new_index(self.config.kv_block_index_config)
        if fleet_health is not None and fleet_health.index is None:
            # Quarantine purges target the same index lookups read.
            fleet_health.bind_index(self.kv_block_index)

        # Scorer tier weights follow the top-level backend configs, like the
        # reference's override in NewKVCacheIndexer (indexer.go:93-98).
        self.config.scorer_config.backend_configs = self.config.backend_configs
        self.scorer = new_kv_block_scorer(self.config.scorer_config)

        self.tokenizers_pool = tokenization_pool or TokenizationPool(
            self.config.tokenizers_pool_config,
            prefix_store=self.prefix_store,
            chat_templating=chat_templating,
        )

    def run(self) -> None:
        """Start the tokenization workers."""
        self.tokenizers_pool.run()

    def shutdown(self) -> None:
        self.tokenizers_pool.shutdown()

    def get_pod_scores(
        self,
        prompt: str,
        model_name: str,
        pod_identifiers: Sequence[str],
        render_request=None,
        lora_id=None,
    ) -> Dict[str, float]:
        """Score pods by cached-prefix length for `prompt`.

        Empty `pod_identifiers` means all known pods are relevant. Returns
        {pod_identifier: score}; pods without hits are absent. `lora_id`
        scopes the lookup to blocks cached under that adapter.
        """
        return self.get_pod_scores_ex(
            prompt, model_name, pod_identifiers,
            render_request=render_request, lora_id=lora_id,
        ).scores

    def get_pod_scores_ex(
        self,
        prompt: str,
        model_name: str,
        pod_identifiers: Sequence[str],
        render_request=None,
        lora_id=None,
        _explain: Optional[dict] = None,
    ) -> PodScores:
        """`get_pod_scores` plus the transfer-plane signal: per-pod matched
        prefix lengths and the prompt's block-hash chain. The scores dict
        is bit-identical to `get_pod_scores` (same derivation, same scorer
        arithmetic, same fleet-health filtering); the extra fields let the
        router drive the data plane's prefetch queue with the exact blocks
        the chosen pod will miss, instead of discarding what the scorer
        already computed.

        `_explain` (score-explain plumbing — `explain_scores` is the public
        face): when a dict is passed, the intermediate stages deposit their
        evidence into it. Explain therefore runs THIS code path, not a
        parallel reimplementation, which is what makes its scores
        bit-identical by construction."""
        # No meta dict on the hot path — the model rides in the explain
        # report; a per-request dict alloc is measurable at this depth.
        with obs.request("read.get_pod_scores"):
            return self._get_pod_scores_ex(
                prompt, model_name, pod_identifiers,
                render_request=render_request, lora_id=lora_id,
                _explain=_explain,
            )

    def _get_pod_scores_ex(
        self,
        prompt: str,
        model_name: str,
        pod_identifiers: Sequence[str],
        render_request=None,
        lora_id=None,
        _explain: Optional[dict] = None,
    ) -> PodScores:
        # Same validation as the event-ingest side (kvevents/pool.py): an
        # invalid adapter id degrades to the base keyspace rather than
        # hashing into a keyspace no event can ever populate.
        if not isinstance(lora_id, int) or isinstance(lora_id, bool) or lora_id < 0:
            if lora_id is not None:
                kvlog.trace(logger, "ignoring invalid lora_id %r", lora_id)
            lora_id = None

        try:
            with obs.stage("read.tokenize", nested=True):
                tokenized = self.tokenizers_pool.tokenize_ex(
                    render_request, prompt, model_name
                )
        except PoolOverloadedError:
            # Degrade, don't fail: an empty score map routes the request by
            # the caller's fallback strategy, which beats queueing the read
            # path without bound behind a saturated tokenizer.
            logger.warning(
                "tokenization pool overloaded; returning empty scores for model %s",
                model_name,
            )
            if _explain is not None:
                _explain["degraded"] = "tokenization_overloaded"
            return PodScores()

        # The pool's prefix-store boundary state rides along so the chain
        # memo can resume key derivation at the first novel block of a
        # follow-up turn — same keys, none of the re-hashing.
        with obs.stage("read.derive"):
            block_keys = self.token_processor.tokens_to_kv_block_keys(
                None, tokenized.tokens, model_name, lora_id=lora_id,
                prefix_state=tokenized.prefix_state,
            )
        if _explain is not None:
            memo = self.token_processor.chain_memo
            _explain["tokens"] = len(tokenized.tokens)
            _explain["blocks"] = len(block_keys)
            _explain["lora_id"] = lora_id
            _explain["chain_memo"] = (
                {"family": memo.last_family(), "stats": memo.stats()}
                if memo is not None
                else None
            )
        if not block_keys:
            kvlog.trace(logger, "no block keys for prompt, returning empty scores")
            if _explain is not None:
                _explain.setdefault("degraded", "no_block_keys")
            return PodScores()

        if self.popularity is not None:
            # Hot-prefix detection (placement/): the chain head + tenant
            # extra this request routed under, plus the leading token slice
            # a replication warm-up would need. Pure observation — nothing
            # below reads the tracker.
            self.popularity.observe_route(
                [k.chunk_hash for k in block_keys],
                tokens=tokenized.tokens,
                lora_id=lora_id,
                model_name=model_name,
                block_size=self.token_processor.block_size,
            )
        if self.prediction is not None:
            # Session prediction (prediction/): continuation detection +
            # think-time learning over the same chain the scorer is about
            # to walk. Pure observation — nothing below reads the table.
            self.prediction.observe_route(
                [k.chunk_hash for k in block_keys],
                tokens=tokenized.tokens,
                lora_id=lora_id,
                model_name=model_name,
                block_size=self.token_processor.block_size,
            )

        with obs.stage("read.lookup"):
            key_to_pods = self.kv_block_index.lookup(
                block_keys, set(pod_identifiers)
            )
        with obs.stage("read.score"):
            scores, match_blocks = self.scorer.score_ex(block_keys, key_to_pods)
            if _explain is not None:
                _explain["raw_scores"] = dict(scores)
            if self.fleet_health is not None:
                # Degraded-mode scoring: suspect pods demoted, stale pods
                # excluded. An emptied map is the explicit no-cache-signal
                # answer — the caller's load/round-robin fallback takes over
                # instead of routing to phantom placements.
                scores = self.fleet_health.filter_scores(scores)
            if self.antientropy is not None:
                scores = self.antientropy.adjust_scores(scores)
            if self.routing_policy is not None:
                scores = self.routing_policy.adjust(scores, _explain=_explain)
        kvlog.trace(logger, "pod scores: %s", scores)
        return PodScores(
            scores=scores,
            match_blocks=match_blocks,
            block_hashes=[k.chunk_hash for k in block_keys],
        )

    def score_many(self, requests: Sequence[ScoreRequest]) -> List[PodScores]:
        """Bulk read path: score a router batch in one call, amortizing
        every stage across the batch — the tokenization pool chews all
        items in parallel (batch latency is max-of-items, not
        sum-of-items), derivation dedupes shared prefixes through one
        chain-memo probe and at most two native hash crossings, the index
        crosses each lock once per batch (`Index.lookup_many`), and the
        scorer reuses per-block weight maps across items sharing a prefix.

        Results are BIT-IDENTICAL to `[get_pod_scores_ex(r) for r in
        requests]` over the same state — pinned by tests/test_score_many.py
        across all four index backends, LoRA keyspaces, fleet-health
        states, and the cluster scatter-gather front. Degradation is per
        ITEM: a request shed by a saturated tokenization pool returns an
        empty `PodScores` in its slot while the rest of the batch scores
        normally (the single-call overload contract, item-scoped).

        One root trace (`read.score_many`) covers the batch, with
        `read.batch.*` stage spans plus the pool workers' per-item spans
        recorded into it — stage attribution shows exactly where the
        amortization lands."""
        if not requests:
            return []
        with obs.request("read.score_many", {"batch": len(requests)}):
            return self._score_many(requests)

    def _score_many(self, requests: Sequence[ScoreRequest]) -> List[PodScores]:
        n = len(requests)
        results: List[Optional[PodScores]] = [None] * n

        # Same per-item adapter-id validation as the single-call path.
        loras: List[Optional[int]] = []
        for r in requests:
            lora_id = r.lora_id
            if (
                not isinstance(lora_id, int) or isinstance(lora_id, bool)
                or lora_id < 0
            ):
                if lora_id is not None:
                    kvlog.trace(logger, "ignoring invalid lora_id %r", lora_id)
                lora_id = None
            loras.append(lora_id)

        with obs.stage("read.batch.tokenize", nested=True):
            tokenized = self.tokenizers_pool.tokenize_many(
                [(r.render_request, r.prompt, r.model_name) for r in requests]
            )
        live: List[int] = []
        shed = 0
        for i, t in enumerate(tokenized):
            if isinstance(t, PoolOverloadedError):
                # Per-item degradation: one shed item never degrades the
                # batch — its slot carries the explicit no-signal answer.
                results[i] = PodScores()
                shed += 1
            else:
                live.append(i)
        if shed:
            logger.warning(
                "tokenization pool overloaded; %d/%d batch item(s) "
                "degraded to empty scores", shed, n,
            )

        with obs.stage("read.batch.derive"):
            keys_per_item = self.token_processor.tokens_to_kv_block_keys_many([
                (
                    tokenized[i].tokens, requests[i].model_name, loras[i],
                    tokenized[i].prefix_state,
                )
                for i in live
            ])

        # Relevant-pod sets are built ONCE per distinct pod list and reused
        # across the batch (the single-call path rebuilds per call).
        #
        # Prefix-sharing plan: items whose chains share their FIRST key
        # OBJECT under the same pod filter share a leading prefix — the
        # chain memo hands requests over a common system prefix the same
        # Key objects, so a zip-`is` scan finds the shared span at pointer
        # speed. The first such item becomes the bucket's reference and is
        # looked up (and walked by the scorer) in full; every later member
        # looks up only its TAIL past the shared span and forks the
        # reference's walk state at the divergence point
        # (`scorer.score_plan`). Bit-identity: the shared span contributes
        # the exact same entry lists and float additions either way —
        # sharing only moves who performs the walk. Cold chains (distinct
        # objects for equal hashes) simply never match: correct, just
        # unamortized.
        pod_sets: dict = {}
        buckets: dict = {}          # (id(pod_set), id(keys[0])) -> plan pos
        plan_specs: List[dict] = []  # one per scored item, plan order
        lookup_reqs: List[tuple] = []
        for pos, i in enumerate(live):
            block_keys = keys_per_item[pos]
            if not block_keys:
                kvlog.trace(
                    logger, "no block keys for batch item, empty scores"
                )
                results[i] = PodScores()
                continue
            if self.popularity is not None:
                tp = tokenized[i]
                self.popularity.observe_route(
                    [k.chunk_hash for k in block_keys],
                    tokens=tp.tokens,
                    lora_id=loras[i],
                    model_name=requests[i].model_name,
                    block_size=self.token_processor.block_size,
                )
            if self.prediction is not None:
                self.prediction.observe_route(
                    [k.chunk_hash for k in block_keys],
                    tokens=tokenized[i].tokens,
                    lora_id=loras[i],
                    model_name=requests[i].model_name,
                    block_size=self.token_processor.block_size,
                )
            pods = tuple(requests[i].pod_identifiers)
            pod_set = pod_sets.get(pods)
            if pod_set is None:
                pod_set = pod_sets[pods] = set(pods)
            bucket_key = (id(pod_set), id(block_keys[0]))
            ref_pos = buckets.get(bucket_key)
            if ref_pos is None:
                buckets[bucket_key] = len(plan_specs)
                lookup_idx = len(lookup_reqs)
                lookup_reqs.append((block_keys, pod_set))
                plan_specs.append({
                    "item": i, "keys": block_keys, "lookup": lookup_idx,
                    "ref": None, "pods": pods,
                })
            else:
                ref_keys = plan_specs[ref_pos]["keys"]
                shared_blocks = 0
                for a, b in zip(ref_keys, block_keys):
                    if a is not b:
                        break
                    shared_blocks += 1
                tail = block_keys[shared_blocks:]
                lookup_idx = None
                if tail:
                    lookup_idx = len(lookup_reqs)
                    lookup_reqs.append((tail, pod_set))
                plan_specs.append({
                    "item": i, "keys": block_keys, "lookup": lookup_idx,
                    "ref": ref_pos, "shared": shared_blocks, "tail": tail,
                    "pods": pods,
                })
                plan_specs[ref_pos]["forked"] = True

        if plan_specs:
            native_out = self._native_score_plan(plan_specs)
            if native_out is not None:
                for spec, (scores, match_blocks) in zip(plan_specs, native_out):
                    results[spec["item"]] = PodScores(
                        scores=scores,
                        match_blocks=match_blocks,
                        block_hashes=[k.chunk_hash for k in spec["keys"]],
                    )
                return results
            with obs.stage("read.batch.lookup"):
                lookup_many = getattr(self.kv_block_index, "lookup_many", None)
                if lookup_many is not None:
                    hits = lookup_many(lookup_reqs)
                else:  # duck-typed test doubles without the batch API
                    hits = [
                        self.kv_block_index.lookup(keys, pod_set)
                        for keys, pod_set in lookup_reqs
                    ]
            with obs.stage("read.batch.score"):
                plan: List[tuple] = []
                for spec in plan_specs:
                    if spec["ref"] is None:
                        plan.append((
                            "solo", spec["keys"], hits[spec["lookup"]],
                            spec.get("forked", False),
                        ))
                    else:
                        plan.append((
                            "fork", spec["ref"], spec["shared"], spec["tail"],
                            hits[spec["lookup"]]
                            if spec["lookup"] is not None else {},
                        ))
                scored = self.scorer.score_plan(plan)
                fleet_health = self.fleet_health
                antientropy = self.antientropy
                routing_policy = self.routing_policy
                for spec, (scores, match_blocks) in zip(plan_specs, scored):
                    if fleet_health is not None:
                        scores = fleet_health.filter_scores(scores)
                    if antientropy is not None:
                        scores = antientropy.adjust_scores(scores)
                    if routing_policy is not None:
                        scores = routing_policy.adjust(scores)
                    results[spec["item"]] = PodScores(
                        scores=scores,
                        match_blocks=match_blocks,
                        block_hashes=[k.chunk_hash for k in spec["keys"]],
                    )
        return results

    def _native_score_plan(self, plan_specs):
        """Fused read path: when the index is the native arena, run the
        whole batch plan — lookup + longest-prefix score + fleet-health /
        anti-entropy / routing adjustments — in one GIL-released crossing.

        Returns the per-spec `(scores, match_blocks)` list, or None when
        the backend isn't native (the ordinary Python path, not a
        fallback) or the crossing failed (counted in
        `kvcache_native_fallbacks_total`; the Python path recomputes the
        batch from the same state, so degradation is invisible in the
        scores)."""
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.native_index import (
            NativeScoringIndex,
            count_fallback,
        )

        inner = getattr(self.kv_block_index, "inner", self.kv_block_index)
        if not isinstance(inner, NativeScoringIndex):
            return None
        medium_weights = getattr(self.scorer, "medium_weights", None)
        if medium_weights is None:
            count_fallback()  # custom scorer: parity not provable in C
            return None
        try:
            with obs.stage("read.batch.native"):
                return inner.score_plan(
                    plan_specs,
                    medium_weights,
                    fleet_health=self.fleet_health,
                    antientropy=self.antientropy,
                    routing_policy=self.routing_policy,
                )
        except Exception as e:  # noqa: BLE001 - any native failure must
            # degrade to the Python path, never the read path itself.
            count_fallback()
            logger.warning(
                "native scoring crossing failed; batch fell back to the "
                "Python path: %s", e,
            )
            return None

    def score_hashes(
        self,
        model_name: str,
        block_hashes: Sequence[int],
        pod_identifiers: Sequence[str] = (),
    ) -> PodScores:
        """Score pods over an ALREADY-DERIVED chain: the read path's
        lookup/score/fleet-health/routing-policy stages, minus
        tokenization and key derivation (the caller holds the chain —
        e.g. the anticipatory-prefetch scheduler replaying a session's
        observed chain during its idle window).

        By running the exact same stages over the same live state, a
        decision made here can never disagree with what
        `get_pod_scores_ex` would answer for a prompt deriving this
        chain — which is what lets the predictor target "the pod the
        router would pick" instead of a parallel heuristic. Tenant/LoRA
        scoping needs no extra argument: the adapter id is already mixed
        into every chunk hash."""
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import Key

        if not block_hashes:
            return PodScores()
        block_keys = [Key(model_name, h) for h in block_hashes]
        key_to_pods = self.kv_block_index.lookup(
            block_keys, set(pod_identifiers)
        )
        scores, match_blocks = self.scorer.score_ex(block_keys, key_to_pods)
        if self.fleet_health is not None:
            scores = self.fleet_health.filter_scores(scores)
        if self.antientropy is not None:
            scores = self.antientropy.adjust_scores(scores)
        if self.routing_policy is not None:
            scores = self.routing_policy.adjust(scores)
        return PodScores(
            scores=scores,
            match_blocks=match_blocks,
            block_hashes=list(block_hashes),
        )

    def explain_scores(
        self,
        prompt: str,
        model_name: str,
        pod_identifiers: Sequence[str],
        render_request=None,
        lora_id=None,
    ) -> dict:
        """Score with the decision evidence attached (`/debug/score_explain`).

        Runs the exact `get_pod_scores_ex` pipeline (scores bit-identical to
        `get_pod_scores` — pinned by tests/test_obs.py) and reports, per
        pod: the raw scorer output, the matched-prefix length in blocks,
        the fleet-health state and the adjustment it caused (suspect pods
        demoted ×suspect_demotion_factor, stale pods excluded), plus which
        chain-memo entry family served the derivation and the chosen pod
        under the deterministic best-score/lexicographic tie-break."""
        detail: dict = {}
        result = self.get_pod_scores_ex(
            prompt, model_name, pod_identifiers,
            render_request=render_request, lora_id=lora_id, _explain=detail,
        )
        raw = detail.pop("raw_scores", {})
        final = result.scores
        pods = {}
        for pod in sorted(raw):
            health = (
                self.fleet_health.state_of(pod)
                if self.fleet_health is not None
                else "healthy"
            )
            raw_score = raw[pod]
            if pod not in final:
                adjustment = "excluded"
            elif final[pod] != raw_score:
                adjustment = "demoted"
            else:
                adjustment = "none"
            pods[pod] = {
                "raw_score": raw_score,
                "score": final.get(pod),
                "match_blocks": result.match_blocks.get(pod, 0),
                "matched_ratio": round(
                    result.match_blocks.get(pod, 0)
                    / max(len(result.block_hashes), 1),
                    4,
                ),
                "health": health,
                "adjustment": adjustment,
            }
        chosen = None
        if final:
            best = max(final.values())
            chosen = min(p for p, s in final.items() if s == best)
        return {
            "model": model_name,
            "prompt_chars": len(prompt),
            "scores": final,
            "chosen": chosen,
            "pods": pods,
            **detail,
        }

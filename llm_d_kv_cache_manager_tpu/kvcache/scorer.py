"""Pod scoring from block lookup results.

Parity target: LongestPrefixScorer
(/root/reference/pkg/kvcache/kvblock_scorer.go:76-151): walk block keys in
prompt order; only pods present for block 0 start "active"; each subsequent
block intersects the active set; every hit adds the pod's maximum device-tier
weight for that block (unknown tiers default to 1.0). Pods that drop out keep
the score accumulated so far — the score is the weighted length of the longest
consecutive cached prefix.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from llm_d_kv_cache_manager_tpu.kvcache.backend import (
    KVCacheBackendConfig,
    default_kv_cache_backend_configs,
    weight_map,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import Key, PodEntry

LONGEST_PREFIX_MATCH = "LongestPrefix"


@dataclass
class KVBlockScorerConfig:
    scoring_strategy: str = LONGEST_PREFIX_MATCH
    backend_configs: List[KVCacheBackendConfig] = field(
        default_factory=default_kv_cache_backend_configs
    )


def _pod_max_weights(
    entries: Sequence[PodEntry], weights: Dict[str, float]
) -> Dict[str, float]:
    """One pass over a key's entries → {pod: max device-tier weight}.

    Replaces the per-pod `_max_weight` rescan (O(pods × entries) per key)
    with a single O(entries) pass; scores are bit-identical because the
    same max is taken over the same floats before any addition happens.
    """
    best: Dict[str, float] = {}
    for entry in entries:
        w = weights.get(entry.device_tier, 1.0)
        pod = entry.pod_identifier
        prev = best.get(pod)
        if prev is None or w > prev:
            best[pod] = w
    return best


class LongestPrefixScorer:
    strategy = LONGEST_PREFIX_MATCH

    def __init__(self, medium_weights: Dict[str, float]):
        self.medium_weights = medium_weights

    def score(
        self,
        keys: Sequence[Key],
        key_to_pods: Dict[Key, List[PodEntry]],
    ) -> Dict[str, float]:
        if not keys:
            return {}

        weights = self.medium_weights
        scores = _pod_max_weights(key_to_pods.get(keys[0], []), weights)
        active = set(scores)

        for key in keys[1:]:
            if not active:
                break
            here = _pod_max_weights(key_to_pods.get(key, []), weights)
            active &= here.keys()
            for pod in active:
                scores[pod] += here[pod]

        # Pods that dropped out keep the score accumulated so far; pods that
        # never held block 0 were never admitted to `scores`.
        return scores

    def score_ex(
        self,
        keys: Sequence[Key],
        key_to_pods: Dict[Key, List[PodEntry]],
    ) -> Tuple[Dict[str, float], Dict[str, int]]:
        """(scores, match_blocks): `scores` is bit-identical to `score()`
        (same maxes over the same floats, same addition order);
        `match_blocks[pod]` is the pod's matched-prefix LENGTH in blocks —
        how many consecutive leading keys it holds. The scorer walks that
        prefix anyway to accumulate the score; keeping the count is what
        lets the router hand the data plane the exact tail of the chain
        the chosen pod will miss (the route-driven prefetch), instead of
        throwing the information away after ranking."""
        if not keys:
            return {}, {}

        weights = self.medium_weights
        scores = _pod_max_weights(key_to_pods.get(keys[0], []), weights)
        active = set(scores)
        match = dict.fromkeys(active, 1)

        for key in keys[1:]:
            if not active:
                break
            here = _pod_max_weights(key_to_pods.get(key, []), weights)
            active &= here.keys()
            for pod in active:
                scores[pod] += here[pod]
                match[pod] += 1

        return scores, match

    def score_many_ex(
        self,
        items: Sequence[Tuple[Sequence[Key], Dict[Key, List[PodEntry]]]],
    ) -> List[Tuple[Dict[str, float], Dict[str, int]]]:
        """Batched `score_ex`: one `(keys, key_to_pods)` pair per item,
        one `(scores, match_blocks)` pair back, each bit-identical to a
        standalone `score_ex` call (same maxes over the same floats, same
        per-pod addition order — each pod's sum walks its own chain, so
        set-iteration order never reaches the arithmetic).

        The batch amortizes the weight maps: `_pod_max_weights` builds
        one dict per distinct entry-list object, and the index's
        `lookup_many` hands items that share a key THE SAME entry-list
        object — so the map is computed once and reused across every item
        holding it. Callers that also know WHICH items share a leading
        key-chain prefix (the indexer's `score_many`) use `score_plan`
        instead, which additionally forks the walk state at divergence
        points so a shared prefix is WALKED once, not once per item."""
        return self.score_plan([
            ("solo", keys, key_to_pods, False) for keys, key_to_pods in items
        ])

    def score_plan(
        self, plan: Sequence[tuple]
    ) -> List[Tuple[Dict[str, float], Dict[str, int]]]:
        """Execute a batch scoring plan (the `score_many` read path).

        Plan entries, in order:

          ("solo", keys, key_to_pods, keep_states) — a full `score_ex`
            walk. With `keep_states` the walk snapshots its (scores,
            match, active) state after every processed key, so later
            entries can fork from it.
          ("fork", ref_pos, shared_blocks, tail_keys, tail_key_to_pods) —
            an item whose first `shared_blocks` keys are THE SAME OBJECTS
            as plan[ref_pos]'s leading keys, looked up under the same pod
            filter against the same index state. Its walk resumes from
            the reference's snapshot after `shared_blocks` keys and
            continues over `tail_keys` (the keys past the shared prefix)
            with its own tail lookup result.

        Each result is bit-identical to a standalone `score_ex` over the
        item's full chain: the shared prefix contributes the exact same
        per-pod addition sequence whether walked privately or forked
        (same key objects, same entry lists, same floats, same order) —
        forking only moves WHO walks it. If the reference's walk cut
        before the fork point (missing key / emptied active set), the
        frozen final snapshot is the fork state and the tail contributes
        nothing, exactly as the item's own walk would have cut there."""
        weights = self.medium_weights
        wm_cache: Dict[int, Dict[str, float]] = {}
        states_by_pos: Dict[int, list] = {}
        out: List[Tuple[Dict[str, float], Dict[str, int]]] = []
        for pos, item in enumerate(plan):
            if item[0] == "solo":
                _, keys, key_to_pods, keep_states = item
                if not keys:
                    out.append(({}, {}))
                    continue
                entries = key_to_pods.get(keys[0])
                if entries is None:
                    scores: Dict[str, float] = {}
                    active: set = set()
                    match: Dict[str, int] = {}
                elif len(entries) == 1:
                    # Single-holder fast path (the common shape: most
                    # blocks live on one pod). Identical arithmetic to the
                    # weight-map path — the max over one entry IS that
                    # entry's weight — without building the map.
                    e = entries[0]
                    scores = {e.pod_identifier: weights.get(e.device_tier, 1.0)}
                    active = {e.pod_identifier}
                    match = {e.pod_identifier: 1}
                else:
                    first = wm_cache.get(id(entries))
                    if first is None:
                        first = wm_cache[id(entries)] = _pod_max_weights(
                            entries, weights
                        )
                    # Copy: `scores` is mutated below, the cached map is
                    # shared.
                    scores = dict(first)
                    active = set(scores)
                    match = dict.fromkeys(active, 1)
                states = None
                if keep_states:
                    states = [(dict(scores), dict(match), set(active))]
                for key in keys[1:]:
                    if not active:
                        break
                    entries = key_to_pods.get(key)
                    if entries is None:
                        active = set()
                    elif len(entries) == 1:
                        # active ∩ {pod} then add: same float, same order.
                        e = entries[0]
                        pod = e.pod_identifier
                        if pod in active:
                            if len(active) != 1:
                                active = {pod}
                            scores[pod] += weights.get(e.device_tier, 1.0)
                            match[pod] += 1
                        else:
                            active = set()
                    else:
                        here = wm_cache.get(id(entries))
                        if here is None:
                            here = wm_cache[id(entries)] = _pod_max_weights(
                                entries, weights
                            )
                        active &= here.keys()
                        for pod in active:
                            scores[pod] += here[pod]
                            match[pod] += 1
                    if keep_states:
                        states.append((dict(scores), dict(match), set(active)))
                if keep_states:
                    states_by_pos[pos] = states
                out.append((scores, match))
            else:
                _, ref_pos, shared_blocks, tail_keys, tail_hits = item
                # One snapshot per processed key; a cut freezes the list,
                # and the frozen tail state IS the post-cut state.
                states = states_by_pos[ref_pos]
                s_scores, s_match, s_active = states[
                    min(shared_blocks, len(states)) - 1
                ]
                scores = dict(s_scores)
                match = dict(s_match)
                active = set(s_active)
                for key in tail_keys:
                    if not active:
                        break
                    entries = tail_hits.get(key)
                    if entries is None:
                        active = set()
                    elif len(entries) == 1:
                        e = entries[0]
                        pod = e.pod_identifier
                        if pod in active:
                            if len(active) != 1:
                                active = {pod}
                            scores[pod] += weights.get(e.device_tier, 1.0)
                            match[pod] += 1
                        else:
                            active = set()
                    else:
                        here = wm_cache.get(id(entries))
                        if here is None:
                            here = wm_cache[id(entries)] = _pod_max_weights(
                                entries, weights
                            )
                        active &= here.keys()
                        for pod in active:
                            scores[pod] += here[pod]
                            match[pod] += 1
                out.append((scores, match))
        return out


def new_kv_block_scorer(config: Optional[KVBlockScorerConfig] = None) -> LongestPrefixScorer:
    cfg = config or KVBlockScorerConfig()
    if cfg.scoring_strategy != LONGEST_PREFIX_MATCH:
        raise ValueError(f"unsupported scoring strategy: {cfg.scoring_strategy}")
    return LongestPrefixScorer(weight_map(cfg.backend_configs))

"""Pod scoring from block lookup results.

Parity target: LongestPrefixScorer
(/root/reference/pkg/kvcache/kvblock_scorer.go:76-151): walk block keys in
prompt order; only pods present for block 0 start "active"; each subsequent
block intersects the active set; every hit adds the pod's maximum device-tier
weight for that block (unknown tiers default to 1.0). Pods that drop out keep
the score accumulated so far — the score is the weighted length of the longest
consecutive cached prefix.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from llm_d_kv_cache_manager_tpu.kvcache.backend import (
    KVCacheBackendConfig,
    default_kv_cache_backend_configs,
    weight_map,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import Key, PodEntry

LONGEST_PREFIX_MATCH = "LongestPrefix"


@dataclass
class KVBlockScorerConfig:
    scoring_strategy: str = LONGEST_PREFIX_MATCH
    backend_configs: List[KVCacheBackendConfig] = field(
        default_factory=default_kv_cache_backend_configs
    )


def _pod_max_weights(
    entries: Sequence[PodEntry], weights: Dict[str, float]
) -> Dict[str, float]:
    """One pass over a key's entries → {pod: max device-tier weight}.

    Replaces the per-pod `_max_weight` rescan (O(pods × entries) per key)
    with a single O(entries) pass; scores are bit-identical because the
    same max is taken over the same floats before any addition happens.
    """
    best: Dict[str, float] = {}
    for entry in entries:
        w = weights.get(entry.device_tier, 1.0)
        pod = entry.pod_identifier
        prev = best.get(pod)
        if prev is None or w > prev:
            best[pod] = w
    return best


class LongestPrefixScorer:
    strategy = LONGEST_PREFIX_MATCH

    def __init__(self, medium_weights: Dict[str, float]):
        self.medium_weights = medium_weights

    def score(
        self,
        keys: Sequence[Key],
        key_to_pods: Dict[Key, List[PodEntry]],
    ) -> Dict[str, float]:
        if not keys:
            return {}

        weights = self.medium_weights
        scores = _pod_max_weights(key_to_pods.get(keys[0], []), weights)
        active = set(scores)

        for key in keys[1:]:
            if not active:
                break
            here = _pod_max_weights(key_to_pods.get(key, []), weights)
            active &= here.keys()
            for pod in active:
                scores[pod] += here[pod]

        # Pods that dropped out keep the score accumulated so far; pods that
        # never held block 0 were never admitted to `scores`.
        return scores

    def score_ex(
        self,
        keys: Sequence[Key],
        key_to_pods: Dict[Key, List[PodEntry]],
    ) -> Tuple[Dict[str, float], Dict[str, int]]:
        """(scores, match_blocks): `scores` is bit-identical to `score()`
        (same maxes over the same floats, same addition order);
        `match_blocks[pod]` is the pod's matched-prefix LENGTH in blocks —
        how many consecutive leading keys it holds. The scorer walks that
        prefix anyway to accumulate the score; keeping the count is what
        lets the router hand the data plane the exact tail of the chain
        the chosen pod will miss (the route-driven prefetch), instead of
        throwing the information away after ranking."""
        if not keys:
            return {}, {}

        weights = self.medium_weights
        scores = _pod_max_weights(key_to_pods.get(keys[0], []), weights)
        active = set(scores)
        match = dict.fromkeys(active, 1)

        for key in keys[1:]:
            if not active:
                break
            here = _pod_max_weights(key_to_pods.get(key, []), weights)
            active &= here.keys()
            for pod in active:
                scores[pod] += here[pod]
                match[pod] += 1

        return scores, match


def new_kv_block_scorer(config: Optional[KVBlockScorerConfig] = None) -> LongestPrefixScorer:
    cfg = config or KVBlockScorerConfig()
    if cfg.scoring_strategy != LONGEST_PREFIX_MATCH:
        raise ValueError(f"unsupported scoring strategy: {cfg.scoring_strategy}")
    return LongestPrefixScorer(weight_map(cfg.backend_configs))

"""Pod scoring from block lookup results.

Parity target: LongestPrefixScorer
(/root/reference/pkg/kvcache/kvblock_scorer.go:76-151): walk block keys in
prompt order; only pods present for block 0 start "active"; each subsequent
block intersects the active set; every hit adds the pod's maximum device-tier
weight for that block (unknown tiers default to 1.0). Pods that drop out keep
the score accumulated so far — the score is the weighted length of the longest
consecutive cached prefix.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from llm_d_kv_cache_manager_tpu.kvcache.backend import (
    KVCacheBackendConfig,
    default_kv_cache_backend_configs,
    weight_map,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import Key, PodEntry

LONGEST_PREFIX_MATCH = "LongestPrefix"


@dataclass
class KVBlockScorerConfig:
    scoring_strategy: str = LONGEST_PREFIX_MATCH
    backend_configs: List[KVCacheBackendConfig] = field(
        default_factory=default_kv_cache_backend_configs
    )


def _max_weight(
    entries: Sequence[PodEntry], pod_id: str, weights: Dict[str, float]
) -> float:
    best = 0.0
    for entry in entries:
        if entry.pod_identifier == pod_id:
            w = weights.get(entry.device_tier, 1.0)
            if w > best:
                best = w
    return best


class LongestPrefixScorer:
    strategy = LONGEST_PREFIX_MATCH

    def __init__(self, medium_weights: Dict[str, float]):
        self.medium_weights = medium_weights

    def score(
        self,
        keys: Sequence[Key],
        key_to_pods: Dict[Key, List[PodEntry]],
    ) -> Dict[str, float]:
        if not keys:
            return {}

        pods_first = key_to_pods.get(keys[0], [])
        active = {e.pod_identifier for e in pods_first}
        scores: Dict[str, float] = {
            pod: _max_weight(pods_first, pod, self.medium_weights) for pod in active
        }

        for key in keys[1:]:
            if not active:
                break
            pods_here = key_to_pods.get(key, [])
            active &= {e.pod_identifier for e in pods_here}
            for pod in active:
                scores[pod] += _max_weight(pods_here, pod, self.medium_weights)

        return scores


def new_kv_block_scorer(config: Optional[KVBlockScorerConfig] = None) -> LongestPrefixScorer:
    cfg = config or KVBlockScorerConfig()
    if cfg.scoring_strategy != LONGEST_PREFIX_MATCH:
        raise ValueError(f"unsupported scoring strategy: {cfg.scoring_strategy}")
    return LongestPrefixScorer(weight_map(cfg.backend_configs))

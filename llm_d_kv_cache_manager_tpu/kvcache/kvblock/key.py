"""KV-block key model.

Parity target: Key{ModelName, ChunkHash} and PodEntry{PodIdentifier, DeviceTier}
(/root/reference/pkg/kvcache/kvblock/index.go:137-159).

The index is dual-keyed: an *engine key* carries the block hash reported by the
engine's KVEvents verbatim, while a *request key* is recomputed on the indexer
side from the event's token IDs with the chained CBOR+FNV scheme, so that
read-path lookups (which only ever see tokens) land on the same keys.
"""

from __future__ import annotations

import re
from typing import Container, NamedTuple

_DP_SUFFIX_RE = re.compile(r"@dp\d+$")


def base_pod_identifier(pod_identifier: str) -> str:
    """Strip the DP-rank qualifier the event pool appends ("pod@dp3" →
    "pod"). Routers and address maps know pods by their bare identity; the
    index stores the ranked one so DP>1 caches don't alias."""
    return _DP_SUFFIX_RE.sub("", pod_identifier)


def pod_matches(pod_identifier: str, pod_identifier_set: Container[str]) -> bool:
    """Membership test for lookup filters: a ranked identity matches both
    its exact form and its bare pod name."""
    return (
        pod_identifier in pod_identifier_set
        or base_pod_identifier(pod_identifier) in pod_identifier_set
    )


class Key(NamedTuple):
    model_name: str
    chunk_hash: int  # uint64

    def __str__(self) -> str:
        return f"{self.model_name}@{self.chunk_hash:x}"


class PodEntry(NamedTuple):
    pod_identifier: str
    device_tier: str  # e.g. "hbm" | "host" (TPU tiers; reference used gpu/cpu)

    def __str__(self) -> str:
        return f"{self.pod_identifier}@{self.device_tier}"

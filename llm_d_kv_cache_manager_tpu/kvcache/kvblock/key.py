"""KV-block key model.

Parity target: Key{ModelName, ChunkHash} and PodEntry{PodIdentifier, DeviceTier}
(/root/reference/pkg/kvcache/kvblock/index.go:137-159).

The index is dual-keyed: an *engine key* carries the block hash reported by the
engine's KVEvents verbatim, while a *request key* is recomputed on the indexer
side from the event's token IDs with the chained CBOR+FNV scheme, so that
read-path lookups (which only ever see tokens) land on the same keys.
"""

from __future__ import annotations

from typing import NamedTuple


class Key(NamedTuple):
    model_name: str
    chunk_hash: int  # uint64

    def __str__(self) -> str:
        return f"{self.model_name}@{self.chunk_hash:x}"


class PodEntry(NamedTuple):
    pod_identifier: str
    device_tier: str  # e.g. "hbm" | "host" (TPU tiers; reference used gpu/cpu)

    def __str__(self) -> str:
        return f"{self.pod_identifier}@{self.device_tier}"

"""FNV hashing + canonical CBOR encoding for block-key derivation.

This is the correctness keystone of the whole control plane: a block's request
key is `FNV-64a(canonical_CBOR([parent_u64, [token_u32...], null]))`, chained
block to block, with the root hash `FNV-64a(hash_seed_bytes)` — exactly the
scheme of the reference token processor
(/root/reference/pkg/kvcache/kvblock/token_processor.go:81-112) which in turn
mirrors vLLM's block hashing. The hash seed must equal the engine fleet's
PYTHONHASHSEED or every score silently becomes 0.

The canonical CBOR subset implemented here covers the two payload shapes the
scheme encodes — `[uint, [uint...], None]` (base) and
`[uint, [uint...], [uint...]]` (with extra keys, e.g. a LoRA adapter id) —
per RFC 8949 §4.2.1 (shortest-form integer encodings). A C fast path
(native/fnvcbor.c) batch-hashes both shapes in one Python↔C crossing with
the GIL released when built; this file is the always-available pure-Python
reference implementation, pinned against the C paths byte-for-byte by
tests/test_hash_differential.py. This module is also the repo's single home
for FNV: everything else (kvevents pod sharding, prefix-store state folds,
chain-memo fingerprints) imports fnv32a/fnv64a/fold64 from here.
"""

from __future__ import annotations

import hashlib
from typing import Iterable, List, Optional, Sequence

_FNV64_OFFSET = 0xCBF29CE484222325
_FNV64_PRIME = 0x100000001B3
_FNV32_OFFSET = 0x811C9DC5
_FNV32_PRIME = 0x01000193
_MASK64 = 0xFFFFFFFFFFFFFFFF
_MASK32 = 0xFFFFFFFF


def fnv64a(data: bytes, h: int = _FNV64_OFFSET) -> int:
    for b in data:
        h = ((h ^ b) * _FNV64_PRIME) & _MASK64
    return h


def fnv32a(data: bytes) -> int:
    h = _FNV32_OFFSET
    for b in data:
        h = ((h ^ b) * _FNV32_PRIME) & _MASK32
    return h


def _cbor_uint_head(major: int, value: int, out: bytearray) -> None:
    """Shortest-form CBOR head byte(s) for the given major type and value."""
    mt = major << 5
    if value < 24:
        out.append(mt | value)
    elif value <= 0xFF:
        out.append(mt | 24)
        out.append(value)
    elif value <= 0xFFFF:
        out.append(mt | 25)
        out += value.to_bytes(2, "big")
    elif value <= 0xFFFFFFFF:
        out.append(mt | 26)
        out += value.to_bytes(4, "big")
    else:
        out.append(mt | 27)
        out += value.to_bytes(8, "big")


def cbor_hash_payload(
    parent: int, tokens: Sequence[int], extra: Optional[Sequence[int]] = None
) -> bytes:
    """Canonical CBOR for the 3-element payload [parent, tokens, extra].

    `extra` carries per-block discriminators beyond the token stream — the
    LoRA adapter id, for instance (vLLM mixes "extra keys" into its block
    hashes the same way). None encodes as CBOR null, preserving the base
    scheme byte-for-byte; a sequence encodes as an array of uints.
    """
    out = bytearray()
    out.append(0x83)  # array(3)
    _cbor_uint_head(0, parent, out)
    _cbor_uint_head(4, len(tokens), out)
    for t in tokens:
        _cbor_uint_head(0, int(t), out)
    if extra is None:
        out.append(0xF6)  # null
    else:
        _cbor_uint_head(4, len(extra), out)
        for e in extra:
            _cbor_uint_head(0, int(e), out)
    return bytes(out)


def init_hash(seed: str) -> int:
    """Root parent hash: FNV-64a over the seed string bytes."""
    return fnv64a(seed.encode("utf-8"))


def chunk_hash(
    parent: int, tokens: Sequence[int], extra: Optional[Sequence[int]] = None
) -> int:
    """One link of the chain: FNV-64a over the canonical-CBOR payload."""
    return fnv64a(cbor_hash_payload(parent, tokens, extra))


def _cbor_text(s: str) -> bytes:
    """Canonical CBOR text string (major type 3, shortest-form length)."""
    data = s.encode("utf-8")
    out = bytearray()
    _cbor_uint_head(3, len(data), out)
    return bytes(out) + data


def _sha256_low64(data: bytes) -> int:
    return int.from_bytes(hashlib.sha256(data).digest(), "big") & _MASK64


def sha256_cbor_init_hash(seed: str) -> int:
    """Root parent hash under vLLM's `sha256_cbor_64bit` algorithm: the
    lower 64 bits of sha256 over the canonical-CBOR TEXT encoding of the
    PYTHONHASHSEED string (vLLM v1 `init_none_hash` with that hash fn).

    An empty seed is a HARD ERROR (ADVICE round-5): upstream vLLM
    (v0.9–0.10) draws NONE_HASH from per-process `os.urandom` whenever
    PYTHONHASHSEED is unset or empty — for EVERY hash function, not just
    the pickle-sha256 one (the `hash_fn is sha256` condition upstream only
    gates a warning log). An unseeded fleet's root hash is therefore
    random per engine process, parity with it is impossible by
    construction, and any fixed derivation here (earlier revisions used
    sha256 over CBOR null) silently zeroes every score against a real
    fleet. Note the empty string really can reach us: CPython treats an
    empty PYTHONHASHSEED env var as unset rather than rejecting it."""
    if seed == "":
        raise ValueError(
            "hash_algo='sha256_cbor_64bit' requires a non-empty hash_seed: "
            "an unseeded vLLM fleet derives NONE_HASH from per-process "
            "os.urandom, so no fixed seed can ever match it. Set "
            "PYTHONHASHSEED on every engine pod and configure the same "
            "value as the indexer's hash_seed."
        )
    return _sha256_low64(_cbor_text(seed))


def sha256_cbor_chunk_hash(
    parent: int, tokens: Sequence[int], extra: Optional[Sequence[int]] = None
) -> int:
    """One chain link under vLLM's `sha256_cbor_64bit`: same canonical-CBOR
    payload `[parent, [tokens...], extra|null]` as the FNV scheme, hashed
    with sha256 and truncated to the lower 64 bits."""
    return _sha256_low64(cbor_hash_payload(parent, tokens, extra))


def prefix_hashes(
    parent: int,
    token_chunks: Iterable[Sequence[int]],
    extra: Optional[Sequence[int]] = None,
) -> List[int]:
    """Chained hashes for consecutive token chunks."""
    hashes: List[int] = []
    h = parent
    for chunk in token_chunks:
        h = chunk_hash(h, chunk, extra)
        hashes.append(h)
    return hashes


# Optional native fast path (C extension built from native/): identical
# semantics, ~100x faster on long prompts. Falls back silently if not built.
_native = None
try:  # pragma: no cover - exercised only when the extension is built
    from llm_d_kv_cache_manager_tpu import _kvtpu_native as _native  # type: ignore
except ImportError:
    _native = None

# A stale .so built before the batch API looks native but lacks the new
# entry points; treat it as absent for the paths that need them.
_native_batch = getattr(_native, "batch_prefix_hashes", None)
_native_batch_many = getattr(_native, "batch_prefix_hashes_many", None)
_native_fps = getattr(_native, "token_fingerprints", None)


def have_native() -> bool:
    """True when the C hash core (with the batch API) is importable —
    the `native` pytest marker and /readyz introspection key off this."""
    return _native_batch is not None


def fold64(h: int, v: int) -> int:
    """One step of the 64-bit token fold used for chain-memo fingerprints:
    FNV-1a's xor-multiply applied to a whole 64-bit value per step instead
    of per byte. NOT the block-key hash — cache-key material only
    (kvcache/kvblock/chain_memo.py), where accidental-collision resistance
    is what matters, exactly like the prefix store's xxhash64 chunk keys."""
    return ((h ^ (v & _MASK64)) * _FNV64_PRIME) & _MASK64


def token_fingerprints(
    fp0: int, tokens: Sequence[int], seg_tokens: int
) -> List[int]:
    """Chained fingerprints of `tokens` at every full `seg_tokens` boundary
    (trailing partial segment dropped). The C extension and this pure-Python
    loop are bit-identical (pinned by tests/test_hash_differential.py)."""
    if seg_tokens <= 0:
        raise ValueError("seg_tokens must be positive")
    if _native_fps is not None:
        try:
            return list(_native_fps(fp0, tokens, seg_tokens))
        except (TypeError, OverflowError):
            pass  # exotic token types: fall through to the reference loop
    n = (len(tokens) // seg_tokens) * seg_tokens
    h = fp0
    out: List[int] = []
    for i in range(n):
        h = ((h ^ (int(tokens[i]) & _MASK64)) * _FNV64_PRIME) & _MASK64
        if (i + 1) % seg_tokens == 0:
            out.append(h)
    return out


def prefix_hashes_fast(
    parent: int,
    tokens: Sequence[int],
    block_size: int,
    extra: Optional[Sequence[int]] = None,
    algo: str = "fnv64_cbor",
) -> List[int]:
    """Chunk `tokens` into full blocks of `block_size` and chain-hash them.

    `algo` selects the chain hash: "fnv64_cbor" (reference parity, default)
    or "sha256_cbor_64bit" (vLLM `--prefix-caching-hash-algo` parity). The C
    extension accelerates every fnv64_cbor shape (extra keys included) in a
    single Python↔C crossing with the GIL released; pure Python otherwise.
    """
    n_full = len(tokens) // block_size
    if n_full == 0:
        return []
    if algo == "fnv64_cbor":
        if _native_batch is not None:
            try:
                return list(_native_batch(
                    int(parent), tokens, block_size,
                    None if extra is None else list(extra),
                ))
            except (TypeError, OverflowError):
                # Tokens the C conversion rejects (e.g. floats, negatives):
                # the pure-Python path defines the behavior.
                pass
        elif _native is not None and extra is None:
            # Stale pre-batch extension: it requires genuine Python ints.
            return list(_native.prefix_hashes(
                int(parent), [int(t) for t in tokens], block_size
            ))
    chunks = [tokens[i * block_size:(i + 1) * block_size] for i in range(n_full)]
    if algo == "fnv64_cbor":
        return prefix_hashes(parent, chunks, extra)
    if algo == "sha256_cbor_64bit":
        hashes: List[int] = []
        h = parent
        for chunk in chunks:
            h = sha256_cbor_chunk_hash(h, chunk, extra)
            hashes.append(h)
        return hashes
    raise ValueError(f"unknown hash algo: {algo!r}")


def prefix_hashes_fast_many(
    tasks: Sequence[tuple],
) -> List[List[int]]:
    """Batched `prefix_hashes_fast`: `tasks` is a sequence of
    (parent, tokens, block_size, extra, algo) tuples and the result is one
    hash list per task, bit-identical to calling `prefix_hashes_fast` per
    task. When every task is fnv64_cbor and the batch-capable C core is
    built, the whole batch derives in ONE Python↔C crossing with the GIL
    released (native `batch_prefix_hashes_many`); any other shape — mixed
    algorithms, sha256 tasks, exotic token types the C conversion rejects —
    falls back to the per-task wrapper, which defines the behavior."""
    if not tasks:
        return []
    if _native_batch_many is not None and all(
        t[4] == "fnv64_cbor" for t in tasks
    ):
        try:
            return [
                list(hashes)
                for hashes in _native_batch_many([
                    (
                        int(parent), tokens, block_size,
                        None if extra is None else list(extra),
                    )
                    for parent, tokens, block_size, extra, _ in tasks
                ])
            ]
        except (TypeError, OverflowError):
            pass  # fall through: pure Python defines the behavior
    return [
        prefix_hashes_fast(parent, tokens, block_size, extra, algo=algo)
        for parent, tokens, block_size, extra, algo in tasks
    ]

"""Metrics-instrumented index decorator.

Parity target: instrumentedIndex
(/root/reference/pkg/kvcache/kvblock/instrumented_index.go:25-92): wraps any
Index, emitting admission/eviction counters and, per lookup, the latency plus
the maximum per-pod consecutive hit count.

The per-lookup pod hit-count walk is the expensive part: it re-scans every
(key, entry) pair of the result to rebuild a Counter from scratch, purely to
observe one histogram sample — measurable on a read path whose whole lookup
is ~75µs. Two changes keep the signal without the per-call tax:

- **Strided observation.** `hit_count_stride` observes
  `kvcache_index_max_pod_hit_count` every Nth lookup (1 = seed behavior,
  every call). The histogram is a distribution-shape signal; sampling it
  does not bias it.
- **Shared ingest.** When a placement popularity tracker is attached, the
  same walk that builds the hit counts feeds the tracker's block sketch
  (`observe_lookup`) — blocks that keep getting looked up *and found* are
  reuse evidence. One walk, two consumers; with neither due, no walk at all.
"""

from __future__ import annotations

import time
from collections import Counter as PyCounter
from typing import Dict, List, Optional, Sequence, Set

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import Index
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import Key, PodEntry
from llm_d_kv_cache_manager_tpu.metrics import collector as m

DEFAULT_HIT_COUNT_STRIDE = 1


class InstrumentedIndex(Index):
    def __init__(
        self,
        inner: Index,
        hit_count_stride: int = DEFAULT_HIT_COUNT_STRIDE,
        popularity=None,
    ):
        self.inner = inner
        self.hit_count_stride = max(1, int(hit_count_stride))
        # Optional placement.ChainPopularityTracker: lookup hits feed its
        # block sketch through the same result walk the histogram uses.
        self.popularity = popularity
        self._lookup_count = 0

    def lookup(
        self, request_keys: Sequence[Key], pod_identifier_set: Set[str]
    ) -> Dict[Key, List[PodEntry]]:
        start = time.perf_counter()
        result = self.inner.lookup(request_keys, pod_identifier_set)
        elapsed = time.perf_counter() - start

        if m.index_lookup_requests is not None:
            m.index_lookup_requests.inc()
            m.index_lookup_latency.observe(elapsed)
            m.index_lookup_hits.inc(len(result))
            # Racy increment under concurrent readers only perturbs which
            # lookup gets sampled, never the count of samples per stride
            # window by more than the reader count.
            self._lookup_count += 1
            observe_hits = self._lookup_count % self.hit_count_stride == 0
            if observe_hits or self.popularity is not None:
                hit_counts: PyCounter = PyCounter()
                for entries in result.values():
                    for entry in entries:
                        hit_counts[entry.pod_identifier] += 1
                if observe_hits:
                    m.index_max_pod_hits.observe(
                        max(hit_counts.values()) if hit_counts else 0
                    )
                if self.popularity is not None and result:
                    self.popularity.observe_lookup(
                        [k.chunk_hash for k in result]
                    )
        return result

    def lookup_many(
        self, requests: Sequence[tuple]
    ) -> List[Dict[Key, List[PodEntry]]]:
        """Batched `lookup` (Index.lookup_many): delegates to the wrapped
        backend's batch path and observes ONE latency sample plus summed
        hit counters for the whole batch (requests counted per item). The
        max-pod-hit-count histogram samples at the same per-lookup stride,
        counting each item as one lookup."""
        start = time.perf_counter()
        results = self.inner.lookup_many(requests)
        elapsed = time.perf_counter() - start

        if m.index_lookup_requests is not None:
            m.index_lookup_requests.inc(len(requests))
            m.index_lookup_latency.observe(elapsed)
            m.index_lookup_hits.inc(sum(len(r) for r in results))
            before = self._lookup_count
            self._lookup_count = before + len(requests)
            observe_hits = (
                before // self.hit_count_stride
                != self._lookup_count // self.hit_count_stride
            )
            if observe_hits or self.popularity is not None:
                hit_counts: PyCounter = PyCounter()
                looked_up = set()
                for result in results:
                    for key, entries in result.items():
                        if key in looked_up:
                            continue  # shared entry lists: count keys once
                        looked_up.add(key)
                        for entry in entries:
                            hit_counts[entry.pod_identifier] += 1
                if observe_hits:
                    m.index_max_pod_hits.observe(
                        max(hit_counts.values()) if hit_counts else 0
                    )
                if self.popularity is not None and looked_up:
                    self.popularity.observe_lookup(
                        [k.chunk_hash for k in looked_up]
                    )
        return results

    def add(
        self,
        engine_keys: Sequence[Key],
        request_keys: Sequence[Key],
        entries: Sequence[PodEntry],
    ) -> None:
        self.inner.add(engine_keys, request_keys, entries)
        if m.index_admissions is not None:
            m.index_admissions.inc(len(request_keys))

    def evict(self, engine_key: Key, entries: Sequence[PodEntry]) -> None:
        self.inner.evict(engine_key, entries)
        if m.index_evictions is not None:
            m.index_evictions.inc()

    def get_request_key(self, engine_key: Key) -> Optional[Key]:
        return self.inner.get_request_key(engine_key)

    def remove_pod(self, pod_identifier: str) -> int:
        removed = self.inner.remove_pod(pod_identifier)
        if m.index_evictions is not None and removed:
            m.index_evictions.inc(removed)
        return removed

    def remove_entries(
        self, pod_identifier: str, request_keys, device_tiers=None
    ) -> int:
        removed = self.inner.remove_entries(
            pod_identifier, request_keys, device_tiers
        )
        if m.index_evictions is not None and removed:
            m.index_evictions.inc(removed)
        return removed

    def export_view(self):
        return self.inner.export_view()

    def import_view(self, view) -> int:
        imported = self.inner.import_view(view)
        if m.index_admissions is not None and imported:
            m.index_admissions.inc(imported)
        return imported

"""KV-block index contract + backend selection.

Parity target: the Index interface and NewIndex backend selection
(/root/reference/pkg/kvcache/kvblock/index.go:59-135). The index maps
*request keys* to the set of pods (with device tier) holding that block, and
separately maps *engine keys* to request keys so eviction events — which only
carry engine hashes — can find their entries.

Backend selection order matches the reference: in-memory → cost-aware →
redis/valkey, first configured wins; metrics wrapping is applied last.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import Key, PodEntry


class Index(abc.ABC):
    """Thread-safe KV-block locality index."""

    @abc.abstractmethod
    def lookup(
        self, request_keys: Sequence[Key], pod_identifier_set: Set[str]
    ) -> Dict[Key, List[PodEntry]]:
        """Return pods per key, filtered to `pod_identifier_set` (empty = all).

        Walks keys in order; a key that exists with an empty pod set cuts the
        search (prefix chain broke there). Raises ValueError on empty input.
        """

    def lookup_many(
        self, requests: Sequence[Tuple[Sequence[Key], Set[str]]]
    ) -> List[Dict[Key, Sequence[PodEntry]]]:
        """Batched `lookup` (the `score_many` read path): one
        `(request_keys, pod_identifier_set)` pair per router-batch item,
        one result dict per item, each carrying the same entries in the
        same order as a standalone `lookup` over the same state (per-item
        cut semantics preserved; a backend may hand back immutable tuples
        where `lookup` copies into fresh lists).

        This default runs the per-item loop — correct on any backend.
        Backends with a lock to amortize override it: the sharded index
        crosses each touched segment lock at most once per BATCH, the
        cost-aware index takes its global mutex once, and the Redis index
        folds the whole batch into a single pipelined round trip."""
        return [self.lookup(keys, pods) for keys, pods in requests]

    @abc.abstractmethod
    def add(
        self,
        engine_keys: Sequence[Key],
        request_keys: Sequence[Key],
        entries: Sequence[PodEntry],
    ) -> None:
        """Record that `entries` hold the given blocks (both key spaces)."""

    @abc.abstractmethod
    def evict(self, engine_key: Key, entries: Sequence[PodEntry]) -> None:
        """Remove `entries` from the block identified by its engine key."""

    @abc.abstractmethod
    def get_request_key(self, engine_key: Key) -> Optional[Key]:
        """Resolve an engine key to its request key, or None if unknown."""

    def remove_entries(
        self,
        pod_identifier: str,
        request_keys: Sequence[Key],
        device_tiers: Optional[Set[str]] = None,
    ) -> int:
        """Targeted purge: remove `pod_identifier`'s entries for exactly
        the given request keys (optionally only entries whose tier is in
        `device_tiers`; None = all tiers).

        The anti-entropy repair primitive (antientropy/): where
        `remove_pod` quarantines a whole pod, this surgically drops the
        specific (pod, block) placements that fetch-miss feedback or a
        residency audit proved phantom — the pod's OTHER placements keep
        scoring. Pod matching follows `remove_pod` semantics (a bare pod
        name also matches its DP-ranked identities; `key.pod_matches`).
        Keys left with no pods are dropped from both key spaces, exactly
        as if the view had been exported, filtered, and re-imported
        (pinned per backend by tests/test_antientropy.py). Keys the pod
        has no entry for are no-ops. Returns the number of pod entries
        removed.
        """
        raise NotImplementedError(
            f"{type(self).__name__} does not support remove_entries"
        )

    @abc.abstractmethod
    def remove_pod(self, pod_identifier: str) -> int:
        """Bulk-purge every entry `pod_identifier` holds, in one pass.

        The quarantine primitive (fleethealth/tracker.py): when a pod is
        declared stale/dead its placements must stop scoring NOW, not leak
        until LRU churn or per-block removal events that will never arrive.
        A bare pod name also removes its DP-ranked identities ("pod@dpN");
        a ranked name removes only that rank (`key.pod_matches` semantics,
        same as lookup filters). Keys left with no pods are dropped from
        both key spaces. Returns the number of pod entries removed.
        """

    def export_view(self) -> "IndexView":
        """Project the published read state into a portable `IndexView`.

        The snapshot primitive (cluster/snapshot.py): entries come out in
        recency order (oldest first) so `import_view` into a fresh backend
        reconstructs LRU order, and `get_pod_scores` over the restored
        index is bit-identical to the source (pinned by
        tests/test_cluster.py across all four backends). Best-effort under
        concurrent writers — like `remove_pod`, a racing add may or may
        not be captured; warm-restart callers snapshot a quiesced or
        drained index.
        """
        raise NotImplementedError(
            f"{type(self).__name__} does not support export_view"
        )

    def import_view(self, view: "IndexView") -> int:
        """Load an `export_view` projection into this (fresh) backend.

        Entries are applied oldest-first, re-establishing both key spaces
        and recency order. Import targets an EMPTY index: existing entries
        are kept (imports merge), but recency interleaving with pre-import
        state is unspecified. Returns the number of pod entries imported.
        """
        raise NotImplementedError(
            f"{type(self).__name__} does not support import_view"
        )


@dataclass
class IndexView:
    """Portable projection of an index's read state (export/import_view).

    `entries` holds one row per request key — ``(model_name, chunk_hash,
    ((pod_identifier, device_tier), ...))`` — in recency order, oldest
    first, with each key's pod tuple likewise oldest-first (the order
    `LRUCache.keys()` publishes). `engine_map` rows are
    ``(engine_model, engine_hash, request_model, request_hash)``. Plain
    strings/ints only, so the view serializes to canonical CBOR
    (cluster/snapshot.py) without backend knowledge.
    """

    entries: List[tuple] = field(default_factory=list)
    engine_map: List[tuple] = field(default_factory=list)

    def entry_count(self) -> int:
        """Total pod entries across all keys (the unit `remove_pod` and
        `import_view` count in)."""
        return sum(len(row[2]) for row in self.entries)


@dataclass
class IndexConfig:
    """First non-None backend wins, in field order (reference index.go:67-92)."""

    in_memory_config: Optional["InMemoryIndexConfig"] = None
    cost_aware_config: Optional["CostAwareIndexConfig"] = None
    redis_config: Optional["RedisIndexConfig"] = None
    enable_metrics: bool = False
    metrics_logging_interval_s: float = 60.0
    # InstrumentedIndex: observe kvcache_index_max_pod_hit_count every Nth
    # lookup (the per-lookup pod hit-count walk is the one O(result) pass
    # the wrapper adds; 1 = every call, the historical behavior).
    metrics_hit_count_stride: int = 1
    # In-memory striping (kvblock/sharded.py). When the in-memory backend is
    # selected (explicitly or by default), `sharded=True` builds a
    # lock-striped ShardedIndex over `num_shards` segments instead of the
    # single-lock InMemoryIndex; scores are identical, only contention
    # behavior changes. `recency_refresh_interval` is the touch=False read
    # fast path's refresh cadence (1 = touch every lookup, seed behavior).
    sharded: bool = True
    num_shards: int = 16  # DEFAULT_NUM_SHARDS (sharded.py)
    recency_refresh_interval: int = 64  # DEFAULT_RECENCY_REFRESH (sharded.py)
    # Native scoring core (kvblock/native_index.py): when the in-memory
    # backend is selected, back the index with the C arena so the whole
    # read path (lookup + score + per-pod adjustments) and event digestion
    # run in single GIL-released crossings. Requires `make native`
    # (_kvtpu_kvscore); silently degrades to the Python backend when the
    # module isn't built. Scores are bit-identical either way (pinned by
    # the differential-fuzz suites).
    native: bool = False

    @classmethod
    def default(cls) -> "IndexConfig":
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
            InMemoryIndexConfig,
        )

        return cls(in_memory_config=InMemoryIndexConfig())


def new_index(config: Optional[IndexConfig] = None) -> Index:
    """Build the configured index backend, optionally metrics-instrumented."""
    if config is None:
        config = IndexConfig.default()

    index: Optional[Index] = None
    if config.in_memory_config is not None:
        index = _new_memory_index(config, config.in_memory_config)
    elif config.cost_aware_config is not None:
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.cost_aware import (
            CostAwareMemoryIndex,
        )

        index = CostAwareMemoryIndex(config.cost_aware_config)
    elif config.redis_config is not None:
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.redis_index import RedisIndex

        index = RedisIndex(config.redis_config)
    else:
        index = _new_memory_index(config, None)

    if config.enable_metrics:
        from llm_d_kv_cache_manager_tpu.metrics.collector import (
            register_metrics,
            start_metrics_logging,
        )
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.instrumented import (
            InstrumentedIndex,
        )

        register_metrics()
        start_metrics_logging(config.metrics_logging_interval_s)
        index = InstrumentedIndex(
            index, hit_count_stride=config.metrics_hit_count_stride
        )

    return index


def _new_memory_index(config: IndexConfig, in_memory_config) -> Index:
    """In-memory backend: lock-striped ShardedIndex by default, the seed's
    single-lock InMemoryIndex when `config.sharded` is off."""
    from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
        InMemoryIndex,
        InMemoryIndexConfig,
    )

    if config.native:
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.native_index import (
            NativeIndexConfig,
            NativeScoringIndex,
            have_native_index,
        )

        if have_native_index():
            imc = in_memory_config or InMemoryIndexConfig()
            return NativeScoringIndex(NativeIndexConfig(
                size=imc.size, pod_cache_size=imc.pod_cache_size,
            ))
        # Not built (no `make native`): degrade to the Python backend —
        # same scores, just without the fused crossings.

    if not config.sharded:
        return InMemoryIndex(in_memory_config)

    from llm_d_kv_cache_manager_tpu.kvcache.kvblock.sharded import (
        ShardedIndex,
        ShardedIndexConfig,
    )

    imc = in_memory_config or InMemoryIndexConfig()
    return ShardedIndex(ShardedIndexConfig(
        size=imc.size,
        pod_cache_size=imc.pod_cache_size,
        num_shards=config.num_shards,
        recency_refresh_interval=config.recency_refresh_interval,
    ))

"""Minimal RESP2 (Redis Serialization Protocol) client with pipelining.

The reference depends on redis/go-redis with pipelined lookups for
single-RTT multi-key reads (/root/reference/pkg/kvcache/kvblock/redis.go:163-176).
No Redis client library is vendored in this build, so this module speaks the
protocol directly: a thread-safe connection supporting pipelined command
batches over TCP or Unix sockets, covering the command set the index needs
(PING, SET, GET, DEL, HSET, HDEL, HKEYS, HLEN, FLUSHALL).
"""

from __future__ import annotations

import socket
import threading
from typing import Any, List, Optional, Sequence, Tuple, Union
from urllib.parse import urlparse

RespValue = Union[None, int, bytes, str, list, Exception]


class RespError(Exception):
    """Server-side -ERR reply."""


class RespConnection:
    """One socket, thread-safe, pipelining-capable."""

    def __init__(self, url: str, timeout_s: float = 5.0):
        """`url`: redis://host:port[/db], valkey://host:port, or unix:///path."""
        self.url = _normalize_url(url)
        self.timeout_s = timeout_s
        self._sock: Optional[socket.socket] = None
        self._buf = b""
        self._mu = threading.Lock()

    # -- connection management ----------------------------------------------

    def connect(self) -> None:
        # Under _mu: swapping the socket/buffer while another thread is
        # mid-pipeline would tear its frames (it could read replies
        # belonging to the new connection's SELECT, or crash mid-write).
        with self._mu:
            parsed = urlparse(self.url)
            if parsed.scheme == "unix":
                sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                sock.settimeout(self.timeout_s)
                sock.connect(parsed.path)
            else:
                host = parsed.hostname or "localhost"
                port = parsed.port or 6379
                sock = socket.create_connection(
                    (host, port), timeout=self.timeout_s
                )
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = sock
            self._buf = b""
            db = (urlparse(self.url).path or "").lstrip("/")
            if db and db.isdigit() and db != "0":
                self._execute_locked([("SELECT", db)])

    def close(self) -> None:
        with self._mu:
            if self._sock is not None:
                try:
                    self._sock.close()
                finally:
                    self._sock = None

    # -- command execution ----------------------------------------------------

    def execute(self, *args: Union[str, bytes, int]) -> RespValue:
        """Execute one command; raises RespError on -ERR replies."""
        result = self.pipeline([args])[0]
        if isinstance(result, Exception):
            raise result
        return result

    def pipeline(self, commands: Sequence[Tuple]) -> List[RespValue]:
        """Send all commands in one write, read all replies (single RTT).

        Per-command errors are returned in-place as RespError values (like
        go-redis pipelines), not raised.
        """
        with self._mu:
            return self._execute_locked(commands)

    def ping(self) -> bool:
        return self.execute("PING") in (b"PONG", "PONG")

    # -- internals -----------------------------------------------------------

    def _execute_locked(self, commands: Sequence[Tuple]) -> List[RespValue]:
        if self._sock is None:
            raise ConnectionError("not connected (call connect() first)")
        payload = b"".join(_encode_command(cmd) for cmd in commands)
        try:
            self._sock.sendall(payload)
            return [self._read_reply() for _ in commands]
        except (OSError, ConnectionError):
            # Drop the broken socket so the caller can reconnect.
            try:
                self._sock.close()
            finally:
                self._sock = None
            raise

    def _read_line(self) -> bytes:
        while b"\r\n" not in self._buf:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("redis connection closed")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n + 2:  # payload + trailing \r\n
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("redis connection closed")
            self._buf += chunk
        data, self._buf = self._buf[:n], self._buf[n + 2:]
        return data

    def _read_reply(self) -> RespValue:
        line = self._read_line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest
        if kind == b"-":
            return RespError(rest.decode("utf-8", "replace"))
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n == -1:
                return None
            return self._read_exact(n)
        if kind == b"*":
            n = int(rest)
            if n == -1:
                return None
            return [self._read_reply() for _ in range(n)]
        raise ConnectionError(f"unknown RESP reply type: {line!r}")


def _encode_command(args: Tuple) -> bytes:
    out = [b"*%d\r\n" % len(args)]
    for a in args:
        if isinstance(a, int):
            a = str(a).encode()
        elif isinstance(a, str):
            a = a.encode("utf-8")
        out.append(b"$%d\r\n%s\r\n" % (len(a), a))
    return b"".join(out)


def _normalize_url(url: str) -> str:
    """Accept valkey(s):// as an alias of redis(s)://, bare host:port too."""
    if "://" not in url:
        return f"redis://{url}"
    if url.startswith("valkeys://"):
        return "rediss://" + url[len("valkeys://"):]
    if url.startswith("valkey://"):
        return "redis://" + url[len("valkey://"):]
    return url

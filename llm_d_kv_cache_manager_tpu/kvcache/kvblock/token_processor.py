"""Tokens → chained KV-block keys.

Parity target: ChunkedTokenDatabase
(/root/reference/pkg/kvcache/kvblock/token_processor.go:61-162): tokens are
chunked into full blocks of `block_size` (partial tail dropped; vLLM default
16, TPU deployments commonly 64 per the reference benchmark config), each
block's key is the chained CBOR+FNV-64a hash of (parent_hash, block_tokens),
and an optional parent key continues an existing chain (used by the event
pool when BlockStored events carry a parent block hash).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence

from llm_d_kv_cache_manager_tpu.kvcache.kvblock import hashing
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.chain_memo import (
    ChainMemo,
    ChainMemoConfig,
    PrefixState,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import Key

DEFAULT_BLOCK_SIZE = 16  # vLLM default block size


@dataclass
class TokenProcessorConfig:
    block_size: int = DEFAULT_BLOCK_SIZE
    # Must match the engine fleet's PYTHONHASHSEED (vLLM NONE_HASH alignment).
    hash_seed: str = ""
    # Chain-hash algorithm. "fnv64_cbor" is the reference's scheme
    # (token_processor.go:94-112). "sha256_cbor_64bit" reproduces vLLM v1's
    # `--prefix-caching-hash-algo=sha256_cbor_64bit` bit-for-bit (proven by
    # tests/test_hash_parity.py::TestVllmVectors against the vendored
    # oracle) — pin it when the indexer's request keys must equal the
    # engine's own block hashes rather than merely mapping to them through
    # the dual-key engine→request bookkeeping. sha256_cbor_64bit REQUIRES a
    # non-empty hash_seed: an unseeded vLLM fleet draws a per-process
    # random NONE_HASH (os.urandom, all hash fns), so parity with it is
    # impossible and construction fails loudly instead of scoring zero.
    hash_algo: str = "fnv64_cbor"
    # Chain-state memo (kvblock/chain_memo.py): incremental derivation —
    # follow-up turns resume hashing at the first novel block instead of
    # block 0, and the write plane derives each fleet-shared chain once.
    # Produces bit-identical keys (it only moves WHERE hashing starts);
    # disable to pin the from-scratch path.
    chain_memo: bool = True
    chain_memo_config: ChainMemoConfig = field(default_factory=ChainMemoConfig)

    @classmethod
    def default(cls) -> "TokenProcessorConfig":
        return cls()


class ChunkedTokenDatabase:
    """Converts token sequences into chained KV-block keys."""

    def __init__(self, config: Optional[TokenProcessorConfig] = None):
        self.config = config or TokenProcessorConfig.default()
        if self.config.hash_algo == "fnv64_cbor":
            self._init_hash = hashing.init_hash(self.config.hash_seed)
        elif self.config.hash_algo == "sha256_cbor_64bit":
            self._init_hash = hashing.sha256_cbor_init_hash(
                self.config.hash_seed
            )
        else:
            raise ValueError(
                f"unknown hash_algo: {self.config.hash_algo!r}"
            )
        self.chain_memo: Optional[ChainMemo] = None
        if self.config.chain_memo and self.config.chain_memo_config.enabled:
            self.chain_memo = ChainMemo(self.config.chain_memo_config)

    @property
    def block_size(self) -> int:
        return self.config.block_size

    @property
    def init_hash(self) -> int:
        return self._init_hash

    def tokens_to_kv_block_keys(
        self,
        parent_key: Optional[Key],
        tokens: Sequence[int],
        model_name: str,
        lora_id: Optional[int] = None,
        prefix_state: Optional[PrefixState] = None,
    ) -> List[Key]:
        """Chain-hash full blocks of tokens into Keys; [] if no full block.

        `lora_id` mixes the adapter identity into every block hash (vLLM
        "extra keys" semantics), so the same tokens served through different
        LoRA adapters occupy distinct index entries. The reference parses the
        event's LoraID but drops it (pool.go BlockStored handling; its LoRA
        parity test is a skipped TODO) — here it is first-class, and the
        chunk-boundary × LoRA semantics are pinned against the vendored
        vLLM oracle (tests/test_hash_parity.py
        ::TestChunkBoundaryOracleParity).

        `prefix_state` is the tokenization pool's prefix-store boundary
        fingerprint chain for THIS token list (pool.tokenize_ex). With the
        chain memo enabled it makes warm multi-turn derivation O(boundaries)
        instead of O(tokens); keys are bit-identical either way.
        """
        parent_hash = parent_key.chunk_hash if parent_key is not None else self._init_hash
        extra = None if lora_id is None else [int(lora_id)]
        if self.chain_memo is not None:
            return self.chain_memo.derive_keys(
                model_name, parent_hash, tokens, self.config.block_size,
                extra, self.config.hash_algo, prefix_state=prefix_state,
            )
        hashes = hashing.prefix_hashes_fast(
            parent_hash, tokens, self.config.block_size, extra,
            algo=self.config.hash_algo,
        )
        return [Key(model_name, h) for h in hashes]

    def tokens_to_kv_block_keys_many(
        self, requests: Sequence[tuple]
    ) -> List[List[Key]]:
        """Batched `tokens_to_kv_block_keys` for the `score_many` read
        path: `requests` is a sequence of `(tokens, model_name, lora_id,
        prefix_state)` tuples (parent is always the root hash — the read
        path never continues an engine chain) and the result is one Key
        list per request, bit-identical to per-request derivation.

        With the chain memo enabled the whole batch derives through
        `ChainMemo.derive_keys_many` (one memo probe, intra-batch
        shared-prefix dedup, at most two native crossings); with it
        disabled every request still derives in ONE native crossing via
        `hashing.prefix_hashes_fast_many`."""
        bs = self.config.block_size
        algo = self.config.hash_algo
        root = self._init_hash
        if self.chain_memo is not None:
            return self.chain_memo.derive_keys_many([
                (
                    model_name, root, tokens, bs,
                    None if lora_id is None else [int(lora_id)],
                    algo, prefix_state,
                )
                for tokens, model_name, lora_id, prefix_state in requests
            ])
        hashes_per_request = hashing.prefix_hashes_fast_many([
            (
                root, tokens, bs,
                None if lora_id is None else [int(lora_id)], algo,
            )
            for tokens, model_name, lora_id, _ in requests
        ])
        return [
            [Key(model_name, h) for h in hashes]
            for (_, model_name, _, _), hashes
            in zip(requests, hashes_per_request)
        ]

"""Default in-memory KV-block index: two-level LRU.

Parity target: InMemoryIndex (/root/reference/pkg/kvcache/kvblock/in_memory.go):
an LRU of request-key → per-key pod LRU (capped, default 10 pods/key), plus an
LRU mapping engine keys → request keys. Semantics preserved exactly:

- lookup: a key present with an empty pod cache cuts the search (the prefix
  chain is known to break there). A *missing* key cuts too (a departure from
  the reference, which merely skips it): `LongestPrefixScorer` empties its
  active set at any gap in the chain, so entries past the first missing key
  can never contribute to a score — looking them up is pure wasted lock
  traffic on the read path.
- add: double-checked insertion so concurrent adders share one pod cache.
- evict: resolves engine→request key; removing the last pod removes the key
  from both maps (with a re-check to shrink the race window).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import Index, IndexView
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import Key, PodEntry, pod_matches
from llm_d_kv_cache_manager_tpu.utils.lru import LRUCache
from llm_d_kv_cache_manager_tpu.utils import logging as kvlog

logger = kvlog.get_logger("kvblock.in_memory")

DEFAULT_INDEX_SIZE = 10**8
DEFAULT_PODS_PER_KEY = 10


@dataclass
class InMemoryIndexConfig:
    size: int = DEFAULT_INDEX_SIZE
    pod_cache_size: int = DEFAULT_PODS_PER_KEY


class _PodCache:
    """Per-key LRU of pod entries, guarded for check-and-set sequences."""

    __slots__ = ("cache", "mu")

    def __init__(self, capacity: int):
        self.cache: LRUCache[PodEntry, None] = LRUCache(capacity)
        self.mu = threading.Lock()


class InMemoryIndex(Index):
    def __init__(self, config: Optional[InMemoryIndexConfig] = None):
        cfg = config or InMemoryIndexConfig()
        self._data: LRUCache[Key, _PodCache] = LRUCache(cfg.size)
        self._engine_to_request: LRUCache[Key, Key] = LRUCache(cfg.size)
        self._pod_cache_size = cfg.pod_cache_size

    def lookup(
        self, request_keys: Sequence[Key], pod_identifier_set: Set[str]
    ) -> Dict[Key, List[PodEntry]]:
        if not request_keys:
            raise ValueError("no request keys provided for lookup")

        pods_per_key: Dict[Key, List[PodEntry]] = {}
        for key in request_keys:
            pod_cache = self._data.get(key)
            if pod_cache is None:
                # Gap in the prefix chain: the scorer's active set empties
                # here, so post-gap hits are unusable — stop looking them up.
                kvlog.trace(logger, "key not found, cutting search: %s", key)
                return pods_per_key
            entries = pod_cache.cache.keys()
            if not entries:
                kvlog.trace(logger, "no pods for key, cutting search: %s", key)
                return pods_per_key
            if pod_identifier_set:
                entries = [
                    e for e in entries
                    if pod_matches(e.pod_identifier, pod_identifier_set)
                ]
                if entries:
                    pods_per_key[key] = entries
            else:
                pods_per_key[key] = entries
        return pods_per_key

    def lookup_many(
        self, requests: Sequence[tuple]
    ) -> List[Dict[Key, List[PodEntry]]]:
        """Batched `lookup` (Index.lookup_many): ONE `get_many` over the
        union of every item's keys replaces the per-key lock acquisition
        of N sequential lookups (recency refreshes once per batch instead
        of once per item — an LRU-order difference only, never a score
        difference). Items sharing a key share the materialized entry
        list object, which is what lets the scorer's batch path reuse
        per-key weight maps across items."""
        if not requests:
            return []
        union: List[Key] = []
        for keys, _ in requests:
            if not keys:
                raise ValueError("no request keys provided for lookup")
            union.extend(keys)
        fetched = self._data.get_many(union)
        entries_cache: Dict[Key, list] = {}
        shared: dict = {}
        out: List[Dict[Key, List[PodEntry]]] = []
        for request_keys, pod_identifier_set in requests:
            pods_per_key: Dict[Key, List[PodEntry]] = {}
            for key in request_keys:
                pod_cache = fetched.get(key)
                if pod_cache is None:
                    break  # gap: chain cut for this item only
                entries = entries_cache.get(key)
                if entries is None:
                    entries = entries_cache[key] = pod_cache.cache.keys()
                if not entries:
                    break
                if pod_identifier_set:
                    sk = (id(pod_identifier_set), key)
                    hits = shared.get(sk)
                    if hits is None:
                        hits = shared[sk] = [
                            e for e in entries
                            if pod_matches(e.pod_identifier, pod_identifier_set)
                        ]
                    if hits:
                        pods_per_key[key] = hits
                else:
                    pods_per_key[key] = entries
            out.append(pods_per_key)
        return out

    def add(
        self,
        engine_keys: Sequence[Key],
        request_keys: Sequence[Key],
        entries: Sequence[PodEntry],
    ) -> None:
        if not engine_keys or not request_keys or not entries:
            raise ValueError("no keys or entries provided for adding to index")
        if len(engine_keys) != len(request_keys):
            raise ValueError(
                f"engine/request key length mismatch: {len(engine_keys)} != {len(request_keys)}"
            )

        for engine_key, request_key in zip(engine_keys, request_keys):
            self._engine_to_request.add(engine_key, request_key)

            pod_cache = self._data.get(request_key)
            if pod_cache is None:
                candidate = _PodCache(self._pod_cache_size)
                contained, _ = self._data.contains_or_add(request_key, candidate)
                if contained:
                    pod_cache = self._data.get(request_key)
                    if pod_cache is None:  # evicted in the window; re-add ours
                        self._data.add(request_key, candidate)
                        pod_cache = candidate
                else:
                    pod_cache = candidate

            with pod_cache.mu:
                for entry in entries:
                    pod_cache.cache.add(entry, None)

    def evict(self, engine_key: Key, entries: Sequence[PodEntry]) -> None:
        if not entries:
            raise ValueError("no entries provided for eviction from index")

        request_key = self._engine_to_request.get(engine_key)
        if request_key is None:
            kvlog.trace(logger, "engine key not in index, nothing to evict: %s", engine_key)
            return

        pod_cache = self._data.get(request_key)
        if pod_cache is None:
            self._engine_to_request.remove(engine_key)
            return

        with pod_cache.mu:
            for entry in entries:
                pod_cache.cache.remove(entry)
            is_empty = len(pod_cache.cache) == 0

        if is_empty:
            # Re-check before removal to minimize (not eliminate) the window
            # where a concurrent add repopulates the cache; worst case an
            # empty cache is left behind for LRU to collect.
            current = self._data.get(request_key)
            if current is not None:
                with current.mu:
                    still_empty = len(current.cache) == 0
                if still_empty:
                    self._data.remove(request_key)
                    self._engine_to_request.remove(engine_key)

    def get_request_key(self, engine_key: Key) -> Optional[Key]:
        return self._engine_to_request.get(engine_key)

    def remove_pod(self, pod_identifier: str) -> int:
        """One-pass quarantine purge (Index.remove_pod contract).

        Walks a snapshot of the key space; the same best-effort caveat as
        `evict` applies under concurrency (an add racing the pass can
        repopulate a key, which LRU then collects).
        """
        target = {pod_identifier}
        removed = 0
        emptied = set()
        for request_key, pod_cache in self._data.items():
            with pod_cache.mu:
                victims = [
                    e for e in pod_cache.cache.keys()
                    if pod_matches(e.pod_identifier, target)
                ]
                for entry in victims:
                    pod_cache.cache.remove(entry)
                removed += len(victims)
                is_empty = victims and len(pod_cache.cache) == 0
            if is_empty:
                self._data.remove(request_key)
                emptied.add(request_key)
        if emptied:
            for engine_key, request_key in self._engine_to_request.items():
                if request_key in emptied:
                    self._engine_to_request.remove(engine_key)
        return removed

    def shed(self, fraction: float) -> int:
        """Resource-governor hook: drop the oldest `fraction` of request
        keys — the LRU tail, exactly what capacity eviction would reclaim
        next, so a shed is indistinguishable from running at a smaller
        index. A dropped block stops scoring until its pod re-advertises
        it (re-derivable state, never truth). Returns pod entries removed."""
        fraction = min(max(fraction, 0.0), 1.0)
        if fraction <= 0.0:
            return 0
        removed = 0
        emptied = set()
        keys = self._data.keys()
        for request_key in keys[: int(len(keys) * fraction)]:
            pod_cache = self._data.peek(request_key)
            if pod_cache is None:
                continue
            with pod_cache.mu:
                removed += len(pod_cache.cache)
            self._data.remove(request_key)
            emptied.add(request_key)
        if emptied:
            for engine_key, request_key in self._engine_to_request.items():
                if request_key in emptied:
                    self._engine_to_request.remove(engine_key)
        return removed

    def remove_entries(
        self, pod_identifier: str, request_keys, device_tiers=None
    ) -> int:
        """Targeted purge (Index.remove_entries contract): only the given
        request keys are touched, via `peek` so untouched keys keep their
        recency order — the purge must not perturb what the LRU evicts
        next."""
        target = {pod_identifier}
        removed = 0
        emptied = set()
        for request_key in request_keys:
            pod_cache = self._data.peek(request_key)
            if pod_cache is None:
                continue
            with pod_cache.mu:
                victims = [
                    e for e in pod_cache.cache.keys()
                    if pod_matches(e.pod_identifier, target)
                    and (device_tiers is None or e.device_tier in device_tiers)
                ]
                for entry in victims:
                    pod_cache.cache.remove(entry)
                removed += len(victims)
                is_empty = victims and len(pod_cache.cache) == 0
            if is_empty:
                self._data.remove(request_key)
                emptied.add(request_key)
        if emptied:
            for engine_key, request_key in self._engine_to_request.items():
                if request_key in emptied:
                    self._engine_to_request.remove(engine_key)
        return removed

    def export_view(self) -> IndexView:
        """Snapshot both LRUs oldest-first (Index.export_view contract)."""
        entries = []
        for request_key, pod_cache in self._data.items():
            with pod_cache.mu:
                pods = tuple(
                    (e.pod_identifier, e.device_tier)
                    for e in pod_cache.cache.keys()
                )
            entries.append((request_key.model_name, request_key.chunk_hash, pods))
        engine_map = [
            (ek.model_name, ek.chunk_hash, rk.model_name, rk.chunk_hash)
            for ek, rk in self._engine_to_request.items()
        ]
        return IndexView(entries=entries, engine_map=engine_map)

    def import_view(self, view: IndexView) -> int:
        """Rebuild both key spaces in view order (Index.import_view)."""
        imported = 0
        for model_name, chunk_hash, pods in view.entries:
            request_key = Key(model_name, chunk_hash)
            pod_cache = self._data.get(request_key)
            if pod_cache is None:
                pod_cache = _PodCache(self._pod_cache_size)
                self._data.add(request_key, pod_cache)
            with pod_cache.mu:
                for pod, tier in pods:
                    pod_cache.cache.add(PodEntry(pod, tier), None)
                    imported += 1
        for engine_model, engine_hash, req_model, req_hash in view.engine_map:
            self._engine_to_request.add(
                Key(engine_model, engine_hash), Key(req_model, req_hash)
            )
        return imported

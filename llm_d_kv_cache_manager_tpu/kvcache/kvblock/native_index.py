"""Arena-backed native scoring index: both hot loops in one C crossing.

`NativeScoringIndex` is a full `Index` backend whose published read state
lives in a C arena (`native/kvscore.c`, module `_kvtpu_kvscore`) instead of
Python dict-of-LRU structures. Two entry points collapse the paths that
previously bounced between Python orchestration and C islands:

- **`score_plan`** — the router read path. For a whole `score_many` batch,
  lookup + longest-prefix scoring + the per-pod scalar adjustments
  (fleet-health demotion, anti-entropy accuracy factors, routing-policy
  load demotion) run in ONE GIL-released crossing. The scalar pipelines
  ride along as per-pod factor tables built from the trackers' new
  `score_factors` / `score_divisors` hooks, so scoring never drops back
  into Python between the lookup and the final score map.
- **`apply_batch`** — the event write path. Decoded BlockStored /
  BlockRemoved batches are applied against the same arena with request
  keys chain-derived in C (`kvhash.h`, bit-identical to the
  token_processor), readers staying lock-free throughout (per-node
  seqlocks + a structural epoch instead of `sharded.py`'s GIL-atomic
  published tuples).

Strings never cross into C: pods, tiers and models are interned to dense
ids here (ids from 1; 0 is the C empty sentinel) and entries travel as
`(pod_id << 16) | tier_id` packed ints, exactly the view layout the arena
stores. Boxing back to `PodEntry`/score dicts happens on the way out.

Parity contract (pinned by tests/test_native_core.py and the differential
fuzz suites): every surface is bit-identical to `ShardedIndex` + the
Python scorer/adjustment pipeline, with these documented nuances:

- `score_plan` reads each tracker's factor table once per BATCH (one
  clock read), where the Python path re-reads per item. Identical under
  the frozen clocks the property suites use; immaterial drift otherwise.
- fleet-health demotion modes are computed from the tracker's *expected*
  state without advancing it, and the real `refresh()` — including its
  auto-quarantine purges — runs after the crossing. That preserves the
  Python batch path's ordering, where every lookup happens before the
  first `filter_scores` can purge a newly-stale pod.
- lookups through the native path don't touch per-key recency (the
  sharded backend refreshes recency every Nth read); recency is still
  maintained by adds, evictions and digestion, which is what capacity
  eviction order actually keys off in practice.

The pure-Python path is retained behind `IndexConfig.native` and both
backends run the same test suites; import of the native module is
guarded, so builds without `make native` degrade to the Python path.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import Index, IndexView
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import (
    Key,
    PodEntry,
    pod_matches,
)

_native = None
try:  # pragma: no cover - exercised via have_native_index()
    from llm_d_kv_cache_manager_tpu import _kvtpu_kvscore as _native  # type: ignore
except ImportError:  # pragma: no cover
    _native = None


def have_native_index() -> bool:
    """True when the compiled arena module is importable (`make native`)."""
    return _native is not None


# Process-wide count of batches handed back to the pure-Python path —
# mirrored into kvcache_native_fallbacks_total when metrics are
# registered, kept as a plain int so /readyz can report it either way.
_fallbacks = 0


def count_fallback() -> None:
    global _fallbacks
    _fallbacks += 1
    from llm_d_kv_cache_manager_tpu.metrics import collector as metrics

    metrics.count_native_fallback()


def fallback_total() -> int:
    return _fallbacks


_TIER_MASK = 0xFFFF
_FILTER_CACHE_MAX = 256


@dataclass
class NativeIndexConfig:
    """Capacity knobs, mirroring InMemoryIndexConfig: `size` request keys,
    `pod_cache_size` pod entries per key (the per-key LRU width)."""

    size: int = 10**8
    pod_cache_size: int = 10


class _Interner:
    """str <-> dense-id table. Ids start at 1 (0 = C empty sentinel);
    `by_id[0]` is None. Mutations happen under the owning index's lock;
    reads are GIL-atomic (ids are only published after the string is)."""

    __slots__ = ("ids", "by_id")

    def __init__(self) -> None:
        self.ids: Dict[str, int] = {}
        self.by_id: List[Optional[str]] = [None]

    def intern(self, s: str) -> int:
        i = self.ids.get(s)
        if i is None:
            self.by_id.append(s)
            i = self.ids[s] = len(self.by_id) - 1
        return i


class NativeScoringIndex(Index):
    """`Index` backend over the C arena, plus the fused read/write paths."""

    def __init__(self, config: Optional[NativeIndexConfig] = None):
        if _native is None:
            raise RuntimeError(
                "native scoring core not built: run `make native` "
                "(native/kvscore.c -> _kvtpu_kvscore)"
            )
        self.config = config or NativeIndexConfig()
        self._arena = _native.Arena(
            max_keys=self.config.size,
            pods_per_key=self.config.pod_cache_size,
        )
        self._mu = threading.Lock()
        self._pods = _Interner()
        self._tiers = _Interner()
        self._models = _Interner()
        # Bumped when a NEW pod is interned: invalidates the lex-rank
        # table and the filter-bitmap cache (both are sized/keyed by the
        # pod id space).
        self._pod_epoch = 0
        self._lex_cache: Optional[Tuple[int, List[int]]] = None
        self._filter_cache: Dict[tuple, bytes] = {}
        self._filter_epoch = -1

    # -- interning ---------------------------------------------------------

    def intern_entry(self, pod_identifier: str, device_tier: str) -> int:
        """Packed `(pod_id << 16) | tier_id` for an entry, interning both
        strings. The event-pool digest seam packs entries with this before
        handing shaped batches to `apply_batch`."""
        with self._mu:
            pid = self._pod_id_locked(pod_identifier)
            tid = self._tiers.intern(device_tier)
            if tid > _TIER_MASK:
                raise ValueError("too many distinct device tiers")
        return (pid << 16) | tid

    def model_id(self, model_name: str) -> int:
        with self._mu:
            return self._models.intern(model_name)

    def _pod_id_locked(self, pod: str) -> int:
        i = self._pods.ids.get(pod)
        if i is None:
            self._pods.by_id.append(pod)
            i = self._pods.ids[pod] = len(self._pods.by_id) - 1
            self._pod_epoch += 1
        return i

    def _box_entry(self, packed: int) -> PodEntry:
        return PodEntry(
            self._pods.by_id[packed >> 16],
            self._tiers.by_id[packed & _TIER_MASK],
        )

    def _pod_bitmap_locked(self, pod_set) -> bytes:
        """LSB-first bitmap over pod ids where `pod_matches` accepts the
        interned pod. Ids interned after sizing read as not-matching in C
        (they cannot hold entries the caller could have meant)."""
        by_id = self._pods.by_id
        n = len(by_id)
        bm = bytearray((n + 7) // 8)
        for i in range(1, n):
            if pod_matches(by_id[i], pod_set):
                bm[i >> 3] |= 1 << (i & 7)
        return bytes(bm)

    def _filter_bitmap(self, pods: tuple) -> Optional[bytes]:
        """Cached per-(pod-set, intern-epoch) lookup filter; empty set =
        no filter (None)."""
        if not pods:
            return None
        with self._mu:
            if self._filter_epoch != self._pod_epoch:
                self._filter_cache.clear()
                self._filter_epoch = self._pod_epoch
            bm = self._filter_cache.get(pods)
            if bm is None:
                if len(self._filter_cache) >= _FILTER_CACHE_MAX:
                    self._filter_cache.clear()
                bm = self._pod_bitmap_locked(set(pods))
                self._filter_cache[pods] = bm
        return bm

    def _lex_rank_table(self) -> List[int]:
        """`table[pod_id]` = rank of the pod string in sorted order — the
        C-side stand-in for Python's lexicographic-min argmax tie-break.
        Cached per intern epoch."""
        with self._mu:
            epoch = self._pod_epoch
            cached = self._lex_cache
            if cached is not None and cached[0] == epoch:
                return cached[1]
            names = self._pods.by_id[1:]
            order = sorted(range(len(names)), key=lambda i: names[i])
            table = [len(names)] * (len(names) + 1)
            for rank, idx in enumerate(order):
                table[idx + 1] = rank
            self._lex_cache = (epoch, table)
            return table

    # -- Index contract ----------------------------------------------------

    def lookup(
        self, request_keys: Sequence[Key], pod_identifier_set: Set[str]
    ) -> Dict[Key, List[PodEntry]]:
        if not request_keys:
            raise ValueError("no request keys provided for lookup")
        result: Dict[Key, List[PodEntry]] = {}
        pods = pod_identifier_set
        i, n = 0, len(request_keys)
        while i < n:
            # One lock-free C crossing per run of same-model keys (a
            # router request's chain is single-model; segmentation only
            # matters for hand-built mixed batches).
            model = request_keys[i].model_name
            j = i
            while j < n and request_keys[j].model_name == model:
                j += 1
            mid = self._models.ids.get(model)
            if mid is None:
                break  # unknown model: first key misses -> chain cut
            chains = self._arena.lookup_chain(
                mid, [k.chunk_hash for k in request_keys[i:j]]
            )
            for off, packed_row in enumerate(chains):
                entries = [self._box_entry(p) for p in packed_row]
                if pods:
                    hits = [
                        e for e in entries
                        if pod_matches(e.pod_identifier, pods)
                    ]
                else:
                    hits = entries
                # Filtered-to-empty keys are omitted but do NOT cut the
                # walk (sharded.py semantics); a missing key already cut
                # inside lookup_chain.
                if hits:
                    result[request_keys[i + off]] = hits
            if len(chains) < j - i:
                break
            i = j
        return result

    def add(
        self,
        engine_keys: Sequence[Key],
        request_keys: Sequence[Key],
        entries: Sequence[PodEntry],
    ) -> None:
        eng = [
            (self.model_id(k.model_name), k.chunk_hash) for k in engine_keys
        ]
        req = [
            (self.model_id(k.model_name), k.chunk_hash) for k in request_keys
        ]
        packed = [
            self.intern_entry(e.pod_identifier, e.device_tier)
            for e in entries
        ]
        # The arena raises the contract ValueErrors (empty input, engine/
        # request length mismatch) with the backends' exact messages.
        self._arena.add(eng, req, packed)

    def evict(self, engine_key: Key, entries: Sequence[PodEntry]) -> None:
        if not entries:
            raise ValueError("no entries provided for eviction from index")
        mid = self._models.ids.get(engine_key.model_name)
        if mid is None:
            return  # unknown engine key: no-op, like the Python backends
        packed = [
            self.intern_entry(e.pod_identifier, e.device_tier)
            for e in entries
        ]
        self._arena.evict(mid, engine_key.chunk_hash, packed)

    def get_request_key(self, engine_key: Key) -> Optional[Key]:
        mid = self._models.ids.get(engine_key.model_name)
        if mid is None:
            return None
        res = self._arena.get_request_key(mid, engine_key.chunk_hash)
        if res is None:
            return None
        rm, rh = res
        return Key(self._models.by_id[rm], rh)

    def remove_pod(self, pod_identifier: str) -> int:
        with self._mu:
            bm = self._pod_bitmap_locked({pod_identifier})
        if not any(bm):
            return 0
        return self._arena.remove_matching(bm, None, None)

    def remove_entries(
        self,
        pod_identifier: str,
        request_keys: Sequence[Key],
        device_tiers: Optional[Set[str]] = None,
    ) -> int:
        with self._mu:
            bm = self._pod_bitmap_locked({pod_identifier})
        if not any(bm):
            return 0
        tier_bm: Optional[bytes] = None
        if device_tiers is not None:
            by_id = self._tiers.by_id
            tbm = bytearray((len(by_id) + 7) // 8)
            for i in range(1, len(by_id)):
                if by_id[i] in device_tiers:
                    tbm[i >> 3] |= 1 << (i & 7)
            tier_bm = bytes(tbm)
        pairs = []
        for k in request_keys:
            mid = self._models.ids.get(k.model_name)
            if mid is not None:
                pairs.append((mid, k.chunk_hash))
        if not pairs:
            return 0
        return self._arena.remove_matching(bm, tier_bm, pairs)

    def export_view(self) -> IndexView:
        entry_rows, engine_rows = self._arena.dump()
        models = self._models.by_id
        entries = [
            (
                models[m],
                h,
                tuple(
                    (
                        self._pods.by_id[p >> 16],
                        self._tiers.by_id[p & _TIER_MASK],
                    )
                    for p in packed
                ),
            )
            for (m, h, packed) in entry_rows
        ]
        engine_map = [
            (models[m], h, models[rm], rh)
            for (m, h, rm, rh) in engine_rows
        ]
        return IndexView(entries=entries, engine_map=engine_map)

    def import_view(self, view: IndexView) -> int:
        count = 0
        for model, chunk_hash, pods in view.entries:
            mid = self.model_id(model)
            packed = [self.intern_entry(p, t) for (p, t) in pods]
            count += self._arena.seed_key(mid, chunk_hash, packed)
        for em, eh, rm, rh in view.engine_map:
            self._arena.seed_engine(
                self.model_id(em), eh, self.model_id(rm), rh
            )
        return count

    # -- fused read path ---------------------------------------------------

    def score_plan(
        self,
        plan_specs: Sequence[dict],
        medium_weights: Optional[Dict[str, float]],
        fleet_health=None,
        antientropy=None,
        routing_policy=None,
    ) -> List[Tuple[Dict[str, float], Dict[str, int]]]:
        """The whole router batch in one GIL-released crossing.

        `plan_specs` are the indexer's per-item plan dicts (solo items
        carry `keys`/`pods`; fork items add `ref`/`shared`/`tail`; items
        later forked from are flagged `forked`). Returns one
        `(scores, match_blocks)` pair per spec, bit-identical to
        lookup_many -> score_plan -> filter_scores -> adjust_scores ->
        adjust on the Python path. Trackers participate through their
        factor-table hooks (`score_factors` / `score_divisors`); a
        tracker without the hook raises AttributeError, which the
        indexer's fallback seam converts into a counted Python-path
        retry."""
        by_id = self._pods.by_id
        n_pods = len(by_id)

        tiers = self._tiers.by_id
        if medium_weights:
            tier_w = [
                1.0 if t is None else medium_weights.get(t, 1.0)
                for t in tiers
            ]
        else:
            tier_w = [1.0] * len(tiers)

        health_modes = None
        health_factor = 1.0
        if fleet_health is not None:
            health_modes, health_factor = fleet_health.score_factors(
                by_id[:n_pods]
            )
        ae_factors = None
        if antientropy is not None:
            ae_factors = antientropy.score_factors(by_id[:n_pods])
        divisors = None
        if routing_policy is not None:
            divisors = routing_policy.score_divisors(by_id[:n_pods])

        items = []
        for spec in plan_specs:
            keys = spec["keys"]
            model = keys[0].model_name if keys else ""
            mid = self._models.ids.get(model, 0)  # 0 never matches a node
            ref = spec.get("ref")
            if ref is None:
                hashes = [k.chunk_hash for k in keys]
                ref_pos, shared = -1, 0
            else:
                hashes = [k.chunk_hash for k in spec["tail"]]
                ref_pos, shared = ref, spec["shared"]
            items.append((
                mid,
                hashes,
                self._filter_bitmap(spec["pods"]),
                ref_pos,
                shared,
                bool(spec.get("forked")),
            ))

        raw = self._arena.score_batch(
            items,
            tier_w,
            self._lex_rank_table(),
            health_factor,
            health_modes,
            ae_factors,
            divisors,
        )

        out: List[Tuple[Dict[str, float], Dict[str, int]]] = []
        n_adjusted = 0
        n_overrides = 0
        any_scored = False
        for rows, override, routing_ran in raw:
            scores: Dict[str, float] = {}
            match: Dict[str, int] = {}
            for pid, score, m, dropped in rows:
                pod = by_id[pid]
                match[pod] = m
                if not dropped:
                    scores[pod] = score
            if rows:
                any_scored = True
            n_adjusted += routing_ran
            n_overrides += override
            out.append((scores, match))

        if routing_policy is not None and n_adjusted:
            routing_policy.note_adjusted(n_adjusted, n_overrides)
        # Deferred state machine: the Python path's first non-empty
        # filter_scores call runs refresh() (transitions + auto-quarantine
        # purges) AFTER all of this batch's lookups already happened.
        # score_factors above only *peeked* at expected states; run the
        # real refresh now so purges land with the same ordering.
        if fleet_health is not None and any_scored:
            fleet_health.refresh()
        return out

    # -- fused write path --------------------------------------------------

    def apply_batch(
        self,
        model_name: str,
        root_hash: int,
        block_size: int,
        events: Sequence[tuple],
    ) -> int:
        """Apply shaped BlockStored/BlockRemoved tuples (see kvscore.c
        `apply_batch`) under one crossing; returns blocks applied. Raises
        on conversion errors with the arena untouched, so the event pool
        can fall back to the pure-Python digest for the same batch."""
        return self._arena.apply_batch(
            self.model_id(model_name), root_hash, block_size, events
        )

    # -- introspection -----------------------------------------------------

    def native_status(self) -> dict:
        """Arena occupancy/health for /readyz and /debug/score_explain."""
        st = self._arena.stats()
        st["enabled"] = True
        st["interned_pods"] = len(self._pods.by_id) - 1
        st["interned_tiers"] = len(self._tiers.by_id) - 1
        st["interned_models"] = len(self._models.by_id) - 1
        return st

    def stats(self) -> dict:
        return self._arena.stats()

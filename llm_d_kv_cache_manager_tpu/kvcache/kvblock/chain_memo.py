"""Chain-state memo: incremental block-key derivation for the read path.

PR-2 removed the index-lookup bottleneck; after it, the read path's
remaining recomputation is key derivation itself: every GetPodScores call
re-CBOR-encodes and re-FNV-chains the WHOLE prompt prefix from the root
hash, even though on a multi-turn workload (ShareGPT: 84.7% hit rate, long
shared conversation prefixes) almost all of those chain links were already
derived one turn earlier. This module is the hashing half of what the
reference's prefixstore (`pkg/tokenization/prefixstore`) is for the
tokenization half: amortize shared-prefix work across requests.

The memo caches `(prefix boundary → chain state)` so ChunkedTokenDatabase
resumes hashing at the FIRST NOVEL BLOCK of a follow-up turn instead of
block 0. Entries hold ready-made `Key` tuples (not raw hashes): on a warm
walk the covered prefix costs tuple concatenation, not object
construction. Three entry families share one LRU:

**Request entries.** When the prefix state covers the whole token list
(the pool's warm path always does: it returns exactly the covered-chunk
tokens), the final boundary fingerprint identifies the entire request and
one probe returns the complete key tuple.

**Boundary entries** (the read path). The tokenization prefix store already
walks the prompt's text chunks and returns the cached tokens; each cached
chunk now also carries a fingerprint of its token content, and the pool
folds those into a cumulative `prefix_state`: a tuple of
`(fingerprint, n_tokens)` pairs, one per covered text-chunk boundary
(tokenization/prefixstore/lru_store.py). Because the fingerprint chain is a
pure function of the exact token lists the pool RETURNS, a boundary entry
can never go stale: if the prefix store re-tokenizes (or evicts and
relearns) a chunk differently, the fingerprints change and the memo simply
misses — cold recomputation, never wrong keys. A warm multi-turn lookup
does NO per-token work at all for the covered prefix: one batched LRU get
over ~dozens of boundary keys, then tuple concatenation.

**Segment entries** (everything else: the kvevents write plane, direct
callers without a prompt). Tokens are fingerprinted in fixed segments of
`segment_blocks` blocks by one native C call (`token_fingerprints`,
GIL released; pure-Python fold fallback) and each segment's derived keys
are cached under the running fingerprint. An engine fleet re-storing the
same chains (N pods × same prompt prefix) derives them once.

Correctness model: fingerprints are 64-bit cache keys, not security
hashes. An accidental collision would serve a wrong chain state — the same
accepted risk class as the reference prefix store's xxhash64 chunk keys
(a collision there serves wrong TOKENS). All entry families key their
chains off a derivation identity that folds in the model name, the hash
algorithm, the root/parent hash (hence the hash seed), the block size, and
the LoRA extra-key tuple — extra keys change every block hash, so memo
entries for different adapters can never alias (pinned by
tests/test_chain_memo.py).

Eviction: one LRU (utils/lru.py), same lifecycle discipline as the
tokenization prefix cache it rides alongside; an evicted entry only ever
costs recomputation. Thread-safe: the LRU locks internally and entries are
immutable tuples, so concurrent read-path and write-plane derivations
compose; duplicate inserts are idempotent.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from llm_d_kv_cache_manager_tpu.kvcache.kvblock import hashing
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import Key
from llm_d_kv_cache_manager_tpu.utils.lru import LRUCache

# `prefix_state` as produced by the tokenization pool: ((fp, n_tokens), ...)
# per covered text-chunk boundary, in prompt order. `fp` chains over the
# per-chunk token fingerprints; `n_tokens` is the cumulative token count.
PrefixState = Tuple[Tuple[int, int], ...]

_M64 = 0xFFFFFFFFFFFFFFFF
_PRIME = 0x100000001B3

# Distinct fold bases keep the entry families (and anything a later PR
# adds) in disjoint key chains even for identical token content.
_IDENT_BASIS = 0x9E3779B97F4A7C15
_SEG_TAG = 0x5345474D454E5431  # "SEGMENT1"
_BND_TAG = 0x424F554E44415259  # "BOUNDARY"
_REQ_TAG = 0x5245515545535431  # "REQUEST1"


@dataclass
class ChainMemoConfig:
    enabled: bool = True
    # Entries (requests + boundary states + token segments), not blocks. At
    # the defaults an entry holds at most a handful of keys: 128k entries
    # bound the memo around the same order as the prefix store's 500k token
    # blocks.
    capacity: int = 131072
    # Segment granularity of the token-domain family, in blocks. Smaller =
    # finer reuse on divergent chains, more entries per request.
    segment_blocks: int = 8
    # Boundary entries are written at every `boundary_stride`-th text-chunk
    # boundary (plus the final one), bounding the cold path's insert cost;
    # the walk is gap-tolerant (entries carry their block span), so thinning
    # only coarsens WHERE a follow-up turn resumes, never correctness.
    boundary_stride: int = 2


class ChainMemo:
    """Memoized chained block-key derivation (see module docstring)."""

    def __init__(self, config: Optional[ChainMemoConfig] = None):
        self.config = config or ChainMemoConfig()
        if self.config.capacity <= 0:
            raise ValueError("chain memo capacity must be positive")
        if self.config.segment_blocks <= 0:
            raise ValueError("chain memo segment_blocks must be positive")
        if self.config.boundary_stride <= 0:
            raise ValueError("chain memo boundary_stride must be positive")
        # key u64 → request:  (keys,)
        #           boundary: (start_blocks, delta_keys, parent_after,
        #                      n_blocks_total)
        #           segment:  (delta_keys, parent_after)
        self._cache: LRUCache[int, tuple] = LRUCache(self.config.capacity)
        self._str_fp_cache: dict = {}
        self._mu = threading.Lock()
        self._hits = 0
        self._misses = 0
        self._blocks_reused = 0
        self._blocks_hashed = 0
        # Which entry family served this thread's most recent derivation
        # ("request" / "boundary" / "segment" / "cold") — thread-local, so
        # the score-explain path (obs/) can attribute its own derivation
        # without racing concurrent callers.
        self._last = threading.local()

    # -- identity ----------------------------------------------------------

    def _str_fp(self, s: str) -> int:
        fp = self._str_fp_cache.get(s)
        if fp is None:
            fp = hashing.fnv64a(s.encode("utf-8"))
            # Unbounded in principle; in practice model names and algo tags
            # are a handful of interned strings per deployment.
            self._str_fp_cache[s] = fp
        return fp

    def _ident(
        self, model_name: str, parent: int, block_size: int,
        extra: Optional[Sequence[int]], algo: str,
    ) -> int:
        """Fold the derivation identity: two derivations share memo entries
        iff model, algorithm, root/parent hash, block size and extra tuple
        all match — the conditions under which their key chains are equal."""
        h = _IDENT_BASIS
        for v in (self._str_fp(algo), self._str_fp(model_name), parent,
                  block_size):
            h = ((h ^ (v & _M64)) * _PRIME) & _M64
        if extra is not None:
            h = ((h ^ (len(extra) + 1)) * _PRIME) & _M64
            for e in extra:
                h = ((h ^ (int(e) & _M64)) * _PRIME) & _M64
        return h

    # -- stats -------------------------------------------------------------

    def stats(self) -> dict:
        with self._mu:
            return {
                "entries": len(self._cache),
                "capacity": self.config.capacity,
                "hits": self._hits,
                "misses": self._misses,
                "blocks_reused": self._blocks_reused,
                "blocks_hashed": self._blocks_hashed,
                "native": hashing.have_native(),
            }

    def shed(self, fraction: float) -> int:
        """Resource-governor hook: drop the `fraction` least-recently-used
        memo entries. Pure cache: the next derivation over a dropped
        prefix re-hashes from scratch (bit-identical keys, just slower),
        so shedding trades CPU for memory and nothing else. Returns
        entries dropped."""
        fraction = min(max(fraction, 0.0), 1.0)
        with self._mu:
            n = int(len(self._cache) * fraction)
            for key in self._cache.keys()[:n]:
                self._cache.remove(key)
            return n

    def entries(self) -> int:
        """Memoized prefixes — the resource accountant's O(1) meter read."""
        with self._mu:
            return len(self._cache)

    def _count(self, hit: bool, reused: int, hashed: int) -> None:
        with self._mu:
            if hit:
                self._hits += 1
            else:
                self._misses += 1
            self._blocks_reused += reused
            self._blocks_hashed += hashed

    def _count_batch(
        self, hits: int, misses: int, reused: int, hashed: int
    ) -> None:
        """One lock crossing for a whole batch's counters. Counts carry
        per-ITEM semantics (what N single calls would have reported), so
        hit-rate math stays comparable; intra-batch dedup means the actual
        native hash work can be lower than `blocks_hashed` suggests."""
        with self._mu:
            self._hits += hits
            self._misses += misses
            self._blocks_reused += reused
            self._blocks_hashed += hashed

    def last_family(self) -> Optional[str]:
        """Entry family that served this thread's last `derive_keys` call:
        "request" (whole-key-tuple probe), "boundary" (prefix-store
        boundary chain), "segment" (token-domain segments), or "cold" (no
        memoized prefix — full derivation). None before any call."""
        return getattr(self._last, "family", None)

    # -- derivation --------------------------------------------------------

    def derive_keys(
        self,
        model_name: str,
        parent: int,
        tokens: Sequence[int],
        block_size: int,
        extra: Optional[Sequence[int]],
        algo: str,
        prefix_state: Optional[PrefixState] = None,
    ) -> List[Key]:
        """Chained block Keys for `tokens`, resuming from the longest
        memoized prefix. Bit-identical to from-scratch derivation
        (hashing.prefix_hashes_fast) by construction — the memo only ever
        changes WHERE hashing starts, never what it produces."""
        n_full = len(tokens) // block_size
        self._last.family = "cold"
        if n_full == 0:
            return []
        ident = self._ident(model_name, parent, block_size, extra, algo)
        if prefix_state:
            return self._derive_boundary(
                ident, model_name, parent, tokens, block_size, extra, algo,
                prefix_state, n_full,
            )
        return self._derive_segments(
            ident, model_name, parent, tokens, block_size, extra, algo, n_full
        )

    def _tail_keys(
        self, model_name: str, parent_h: int, tokens: Sequence[int],
        covered_blocks: int, block_size: int, extra, algo: str,
    ) -> List[Key]:
        if covered_blocks * block_size >= len(tokens):
            return []
        return [
            Key(model_name, h)
            for h in hashing.prefix_hashes_fast(
                parent_h, tokens[covered_blocks * block_size:], block_size,
                extra, algo=algo,
            )
        ]

    def _derive_boundary(
        self, ident: int, model_name: str, parent: int, tokens,
        block_size: int, extra, algo: str, prefix_state: PrefixState,
        n_full: int,
    ) -> List[Key]:
        cache = self._cache
        n_tokens = len(tokens)
        last_fp, last_n = prefix_state[-1]

        # Whole-request probe: the pool's warm path returns exactly the
        # covered tokens, so the final boundary identifies the request.
        req_key = None
        if last_n == n_tokens:
            h = ((ident ^ _REQ_TAG) * _PRIME) & _M64
            h = ((h ^ last_fp) * _PRIME) & _M64
            req_key = ((h ^ n_tokens) * _PRIME) & _M64
            entry = cache.get(req_key)
            if entry is not None:
                keys = entry[0]
                self._last.family = "request"
                self._count(True, len(keys), 0)
                return list(keys)

        bnd_root = ((ident ^ _BND_TAG) * _PRIME) & _M64
        bnd_keys = [
            ((((bnd_root ^ fp) * _PRIME) & _M64) ^ n_tok) * _PRIME & _M64
            for fp, n_tok in prefix_state
        ]
        found = cache.get_many(bnd_keys)
        keys: List[Key] = []
        parent_h = parent
        covered = 0  # blocks
        hit_boundaries = 0
        # Gap-tolerant walk: entries carry their block span, so a hit whose
        # span starts exactly where we left off extends the chain even when
        # intermediate boundaries were never written (insert stride) or
        # were evicted.
        for bk in bnd_keys:
            entry = found.get(bk)
            if entry is not None and len(entry) == 4 and entry[0] == covered:
                _, delta, parent_after, n_blocks = entry
                keys.extend(delta)
                parent_h = parent_after
                covered = n_blocks
                hit_boundaries += 1
        tail = self._tail_keys(
            model_name, parent_h, tokens, covered, block_size, extra, algo
        )
        full = keys + tail
        inserts = []
        # Record (a strided subset of) the boundaries past the covered
        # prefix — nothing to record when the walk already covered every
        # derived block. Boundary token counts are clamped to the blocks
        # this call actually derived (the last text chunk can cover tokens
        # past the final full block).
        if tail and hit_boundaries < len(prefix_state):
            stride = self.config.boundary_stride
            prev_blocks = covered
            last_i = len(prefix_state) - 1
            for i in range(len(prefix_state)):
                if i % stride != stride - 1 and i != last_i:
                    continue
                n_blocks = min(prefix_state[i][1] // block_size, n_full)
                if n_blocks < prev_blocks:
                    continue  # inside the already-covered prefix
                if bnd_keys[i] in found and n_blocks == prev_blocks:
                    continue  # already present and nothing new to add
                delta = tuple(full[prev_blocks:n_blocks])
                parent_after = (
                    full[n_blocks - 1].chunk_hash if n_blocks else parent
                )
                inserts.append(
                    (bnd_keys[i], (prev_blocks, delta, parent_after, n_blocks))
                )
                prev_blocks = n_blocks
        if req_key is not None:
            inserts.append((req_key, (tuple(full),)))
        if inserts:
            cache.add_many(inserts)
        if hit_boundaries > 0:
            self._last.family = "boundary"
        self._count(hit_boundaries > 0, covered, len(tail))
        return full

    def _derive_segments(
        self, ident: int, model_name: str, parent: int, tokens,
        block_size: int, extra, algo: str, n_full: int,
    ) -> List[Key]:
        seg_tokens = self.config.segment_blocks * block_size
        seg_root = ((ident ^ _SEG_TAG) * _PRIME) & _M64
        # floor(len/seg_tokens) == floor(n_full/segment_blocks): fingerprints
        # cover exactly the full segments of full blocks.
        fps = hashing.token_fingerprints(seg_root, tokens, seg_tokens)
        found = self._cache.get_many(fps)
        keys: List[Key] = []
        parent_h = parent
        covered_segs = 0
        for fp in fps:
            entry = found.get(fp)
            if entry is None:
                break
            delta, parent_after = entry
            keys.extend(delta)
            parent_h = parent_after
            covered_segs += 1
        sb = self.config.segment_blocks
        tail = self._tail_keys(
            model_name, parent_h, tokens, covered_segs * sb, block_size,
            extra, algo,
        )
        full = keys + tail
        if covered_segs < len(fps):
            inserts = []
            for s in range(covered_segs, len(fps)):
                delta = tuple(full[s * sb:(s + 1) * sb])
                inserts.append((fps[s], (delta, delta[-1].chunk_hash)))
            self._cache.add_many(inserts)
        if covered_segs > 0:
            self._last.family = "segment"
        self._count(covered_segs > 0, covered_segs * sb, len(tail))
        return full

    # -- batched derivation ------------------------------------------------

    def derive_keys_many(
        self, items: Sequence[tuple]
    ) -> List[List[Key]]:
        """Batched `derive_keys`: one request per router-batch item, every
        memo probe folded into a single `get_many` (one LRU lock crossing
        for the whole batch), intra-batch dedup of shared chains, and all
        residual hashing done in at most two native crossings
        (hashing.prefix_hashes_fast_many) instead of one per item.

        `items` is a sequence of `(model_name, parent, tokens, block_size,
        extra, algo, prefix_state)` tuples — `derive_keys`'s argument list.
        Returns one Key list per item, bit-identical to calling
        `derive_keys` per item (the batch only moves WHERE hashing happens
        and shares chains that are already identical by fingerprint — the
        same 64-bit-collision risk class every memo probe accepts).

        Intra-batch sharing: a boundary key folds the cumulative token
        fingerprint of everything before it, so under one derivation
        identity two items sharing their FINAL boundary fingerprint share
        the entire chain up to that boundary. Such items form a chain
        group: the group's chain derives once (from the member with the
        least memo coverage) and every member slices its span out of the
        shared result — B requests over one hot system prefix cost one
        derivation, not B. Identical residual tails (duplicate prompts)
        dedupe the same way by content."""
        n_items = len(items)
        results: List[Optional[List[Key]]] = [None] * n_items
        plans: List[Optional[dict]] = [None] * n_items
        req_probe: List[int] = []

        # -- phase 1: whole-request probe -----------------------------------
        # The warm steady state is every item resolving on its request
        # entry, so probe those FIRST (one small get_many) and only build
        # boundary/segment probe keys for the items that miss — cold-path
        # bookkeeping never taxes the warm batch.
        for i, (model_name, parent, tokens, block_size, extra, algo,
                prefix_state) in enumerate(items):
            n_full = len(tokens) // block_size
            if n_full == 0:
                results[i] = []
                continue
            ident = self._ident(model_name, parent, block_size, extra, algo)
            plan: dict = {"ident": ident, "n_full": n_full, "req_key": None}
            if prefix_state:
                n_tokens = len(tokens)
                last_fp, last_n = prefix_state[-1]
                if last_n == n_tokens:
                    h = ((ident ^ _REQ_TAG) * _PRIME) & _M64
                    h = ((h ^ last_fp) * _PRIME) & _M64
                    req_key = ((h ^ n_tokens) * _PRIME) & _M64
                    plan["req_key"] = req_key
                    req_probe.append(req_key)
            plans[i] = plan

        found_req = self._cache.get_many(req_probe) if req_probe else {}
        hits = misses = reused_total = 0
        probe_keys: List[int] = []
        for i, plan in enumerate(plans):
            if plan is None:
                continue
            req_key = plan["req_key"]
            if req_key is not None:
                entry = found_req.get(req_key)
                if entry is not None:
                    keys = entry[0]
                    results[i] = list(keys)
                    plan["resolved"] = True
                    hits += 1
                    reused_total += len(keys)
                    continue
            (model_name, parent, tokens, block_size, extra, algo,
             prefix_state) = items[i]
            if prefix_state:
                ident = plan["ident"]
                bnd_root = ((ident ^ _BND_TAG) * _PRIME) & _M64
                bnd_keys = [
                    ((((bnd_root ^ fp) * _PRIME) & _M64) ^ n_tok)
                    * _PRIME & _M64
                    for fp, n_tok in prefix_state
                ]
                plan["kind"] = "bnd"
                plan["bnd_keys"] = bnd_keys
                plan["n_bnd"] = prefix_state[-1][1] // block_size
                probe_keys.extend(bnd_keys)
            else:
                seg_tokens = self.config.segment_blocks * block_size
                seg_root = ((plan["ident"] ^ _SEG_TAG) * _PRIME) & _M64
                fps = hashing.token_fingerprints(seg_root, tokens, seg_tokens)
                plan["kind"] = "seg"
                plan["fps"] = fps
                probe_keys.extend(fps)

        found = self._cache.get_many(probe_keys) if probe_keys else {}

        # -- phase 2: probe walk + work planning (no hashing yet) ----------
        chain_groups: dict = {}   # (ident, final bnd key) -> group
        wave1_specs: List[tuple] = []
        direct_tasks: dict = {}   # (ident, parent_h, tail tuple) -> task

        for i, plan in enumerate(plans):
            if plan is None or plan.get("resolved"):
                continue
            (model_name, parent, tokens, block_size, extra, algo,
             prefix_state) = items[i]
            covered_keys: List[Key] = []
            parent_h = parent
            if plan["kind"] == "bnd":
                covered = 0
                hit_boundaries = 0
                for bk in plan["bnd_keys"]:
                    entry = found.get(bk)
                    if (
                        entry is not None and len(entry) == 4
                        and entry[0] == covered
                    ):
                        _, delta, parent_after, n_blocks = entry
                        covered_keys.extend(delta)
                        parent_h = parent_after
                        covered = n_blocks
                        hit_boundaries += 1
                plan["hit_boundaries"] = hit_boundaries
                n_bnd = plan["n_bnd"]
                if covered < n_bnd:
                    # Chain group: everything up to the final boundary is
                    # shared by fingerprint; derive it once per group.
                    gk = (plan["ident"], plan["bnd_keys"][-1])
                    grp = chain_groups.get(gk)
                    if grp is None or covered < grp["covered"]:
                        chain_groups[gk] = {
                            "covered": covered, "parent_h": parent_h,
                            "tokens": tokens, "block_size": block_size,
                            "extra": extra, "algo": algo, "end": n_bnd,
                            "model": model_name,
                        }
                    plan["chain"] = gk
                elif covered < plan["n_full"]:
                    # Memo reached (or passed) the final boundary; the
                    # private tail derives directly.
                    tail_tokens = tokens[covered * block_size:]
                    dk = (plan["ident"], parent_h, tuple(tail_tokens))
                    task = direct_tasks.get(dk)
                    if task is None:
                        task = direct_tasks[dk] = len(wave1_specs)
                        wave1_specs.append((
                            parent_h, tail_tokens, block_size, extra, algo,
                        ))
                    plan["direct"] = task
            else:
                fps = plan["fps"]
                covered_segs = 0
                for fp in fps:
                    entry = found.get(fp)
                    if entry is None:
                        break
                    delta, parent_after = entry
                    covered_keys.extend(delta)
                    parent_h = parent_after
                    covered_segs += 1
                covered = covered_segs * self.config.segment_blocks
                plan["covered_segs"] = covered_segs
                if covered < plan["n_full"]:
                    tail_tokens = tokens[covered * block_size:]
                    dk = (plan["ident"], parent_h, tuple(tail_tokens))
                    task = direct_tasks.get(dk)
                    if task is None:
                        task = direct_tasks[dk] = len(wave1_specs)
                        wave1_specs.append((
                            parent_h, tail_tokens, block_size, extra, algo,
                        ))
                    plan["direct"] = task
            plan["covered"] = covered
            plan["parent_h"] = parent_h
            plan["covered_keys"] = covered_keys

        # -- wave 1: chain groups + direct tails, one native crossing ------
        n_chain = len(chain_groups)
        chain_list = list(chain_groups.items())
        specs = [
            (
                grp["parent_h"],
                grp["tokens"][
                    grp["covered"] * grp["block_size"]:
                    grp["end"] * grp["block_size"]
                ],
                grp["block_size"], grp["extra"], grp["algo"],
            )
            for _, grp in chain_list
        ] + wave1_specs
        wave1_out = hashing.prefix_hashes_fast_many(specs)
        for idx, (_, grp) in enumerate(chain_list):
            hashes = wave1_out[idx]
            grp["keys"] = [Key(grp["model"], h) for h in hashes]
            grp["end_parent"] = hashes[-1]
        direct_keys: List[List[Key]] = []
        for task in range(len(wave1_specs)):
            direct_keys.append(None)  # filled below, model comes per item
        # Direct-tail Key lists are shared across deduped items; build each
        # once with the first referencing item's model name (the identity
        # fold guarantees members share it).
        for i, plan in enumerate(plans):
            if plan is None or plan.get("resolved") or "direct" not in plan:
                continue
            task = plan["direct"]
            if direct_keys[task] is None:
                model_name = items[i][0]
                direct_keys[task] = [
                    Key(model_name, h) for h in wave1_out[n_chain + task]
                ]

        # -- wave 2: private tails past a chain group's final boundary -----
        wave2_specs: List[tuple] = []
        wave2_tasks: dict = {}
        for i, plan in enumerate(plans):
            if plan is None or plan.get("resolved") or "chain" not in plan:
                continue
            if plan["n_full"] <= plan["n_bnd"]:
                continue
            (model_name, parent, tokens, block_size, extra, algo,
             prefix_state) = items[i]
            grp = chain_groups[plan["chain"]]
            tail_tokens = tokens[plan["n_bnd"] * block_size:]
            wk = (plan["ident"], plan["chain"][1], tuple(tail_tokens))
            task = wave2_tasks.get(wk)
            if task is None:
                task = wave2_tasks[wk] = len(wave2_specs)
                wave2_specs.append((
                    grp["end_parent"], tail_tokens, block_size, extra, algo,
                ))
            plan["wave2"] = task
        wave2_out = (
            hashing.prefix_hashes_fast_many(wave2_specs)
            if wave2_specs else []
        )
        wave2_keys: List[Optional[List[Key]]] = [None] * len(wave2_specs)

        # -- assembly + memo inserts ---------------------------------------
        inserts: List[tuple] = []
        for i, plan in enumerate(plans):
            if plan is None or plan.get("resolved"):
                continue
            (model_name, parent, tokens, block_size, extra, algo,
             prefix_state) = items[i]
            covered = plan["covered"]
            full = plan["covered_keys"]
            if "chain" in plan:
                grp = chain_groups[plan["chain"]]
                full = full + grp["keys"][covered - grp["covered"]:]
                if "wave2" in plan:
                    task = plan["wave2"]
                    if wave2_keys[task] is None:
                        wave2_keys[task] = [
                            Key(model_name, h) for h in wave2_out[task]
                        ]
                    full = full + wave2_keys[task]
            elif "direct" in plan:
                full = full + direct_keys[plan["direct"]]
            results[i] = full
            new_keys = len(full) - covered
            if plan["kind"] == "bnd":
                hit = plan["hit_boundaries"] > 0
                bnd_keys = plan["bnd_keys"]
                if new_keys and plan["hit_boundaries"] < len(prefix_state):
                    # Same strided insert policy as the single-item path.
                    stride = self.config.boundary_stride
                    prev_blocks = covered
                    last_j = len(prefix_state) - 1
                    n_full = plan["n_full"]
                    for j in range(len(prefix_state)):
                        if j % stride != stride - 1 and j != last_j:
                            continue
                        n_blocks = min(
                            prefix_state[j][1] // block_size, n_full
                        )
                        if n_blocks < prev_blocks:
                            continue
                        if bnd_keys[j] in found and n_blocks == prev_blocks:
                            continue
                        delta = tuple(full[prev_blocks:n_blocks])
                        parent_after = (
                            full[n_blocks - 1].chunk_hash
                            if n_blocks else parent
                        )
                        inserts.append((
                            bnd_keys[j],
                            (prev_blocks, delta, parent_after, n_blocks),
                        ))
                        prev_blocks = n_blocks
                if plan["req_key"] is not None:
                    inserts.append((plan["req_key"], (tuple(full),)))
            else:
                hit = plan["covered_segs"] > 0
                fps = plan["fps"]
                sb = self.config.segment_blocks
                if plan["covered_segs"] < len(fps):
                    for s in range(plan["covered_segs"], len(fps)):
                        delta = tuple(full[s * sb:(s + 1) * sb])
                        inserts.append((fps[s], (delta, delta[-1].chunk_hash)))
            if hit:
                hits += 1
            else:
                misses += 1
            reused_total += covered

        if inserts:
            self._cache.add_many(inserts)
        hashed_total = sum(
            len(r) - p["covered"]
            for r, p in zip(results, plans)
            if p is not None and not p.get("resolved")
        )
        self._last.family = "batch"
        self._count_batch(hits, misses, reused_total, hashed_total)
        return results

"""Cost-aware (byte-budgeted) in-memory index.

Parity target: CostAwareMemoryIndex
(/root/reference/pkg/kvcache/kvblock/cost_aware_memory.go): instead of
bounding the index by entry *count*, bound it by estimated resident *bytes*
(config accepts human-readable sizes like "2GiB"). Where the reference uses
ristretto's cost-based admission, this build uses an LRU whose eviction is
driven by accumulated entry cost — same contract (stay under the byte
budget), simpler machinery.
"""

from __future__ import annotations

import sys
import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Union

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import Index, IndexView
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import Key, PodEntry, pod_matches
from llm_d_kv_cache_manager_tpu.utils.humansize import parse_human_size
from llm_d_kv_cache_manager_tpu.utils.lru import LRUCache

DEFAULT_MAX_SIZE = "1GiB"
DEFAULT_PODS_PER_KEY = 10

# Fixed per-object overheads (dict/list headers etc.) — an estimate, like the
# reference's CalculateByteSize (cost_aware_memory.go:126-158).
_ENTRY_OVERHEAD = 64


def calculate_byte_size(key: Key, entries: Sequence[PodEntry]) -> int:
    size = _ENTRY_OVERHEAD + len(key.model_name) + 8
    for e in entries:
        size += _ENTRY_OVERHEAD + len(e.pod_identifier) + len(e.device_tier)
    return size


@dataclass
class CostAwareIndexConfig:
    max_size_bytes: Union[int, str] = DEFAULT_MAX_SIZE
    pod_cache_size: int = DEFAULT_PODS_PER_KEY
    # Popularity-weighted eviction (placement/): when a popularity tracker
    # is bound, pressure evicts the lowest-retention key among this many
    # LRU-oldest candidates instead of strictly the oldest. 1 (default)
    # is pure LRU — bit-identical to the pre-placement backend whether or
    # not a tracker is bound.
    eviction_sample: int = 1


class _CostedPodCache:
    __slots__ = ("cache", "mu", "cost")

    def __init__(self, capacity: int):
        self.cache: LRUCache[PodEntry, None] = LRUCache(capacity)
        self.mu = threading.Lock()
        self.cost = 0


class CostAwareMemoryIndex(Index):
    """Byte-budget-bounded index; evicts least-recently-used keys on pressure."""

    def __init__(self, config: Optional[CostAwareIndexConfig] = None):
        cfg = config or CostAwareIndexConfig()
        self._budget = parse_human_size(cfg.max_size_bytes)
        self._pod_cache_size = cfg.pod_cache_size
        self._eviction_sample = max(1, cfg.eviction_sample)
        self._data: "OrderedDict[Key, _CostedPodCache]" = OrderedDict()
        self._engine_to_request: Dict[Key, Key] = {}
        self._request_to_engines: Dict[Key, Set[Key]] = {}
        self._total_cost = 0
        self._mu = threading.Lock()
        # Placement integration (bind_popularity): eviction weighs decayed
        # block popularity against what re-landing the block would cost.
        self._popularity = None
        self._reland_cost_model = None
        self.eviction_stats = {"lru": 0, "weighted": 0}

    def bind_popularity(self, tracker, cost_model=None) -> None:
        """Attach a placement popularity tracker (and optionally an
        engine/costs.TransferCostModel) to eviction.

        Under byte pressure the victim becomes the key with the lowest
        *retention value* among the `eviction_sample` LRU-oldest
        candidates, where retention = decayed block popularity x the
        per-token seconds losing the placement would cost the fleet: with
        a cost model, `recompute_s` for a block only resident in device
        tiers, `staged_restore_s` when a host-tier copy exists (the
        knowledge is cheaper to rebuild, so the entry is less sticky);
        without one, popularity alone ranks the window. Hot replicated
        prefixes therefore stay pinned while the cold long tail drains in
        LRU order — and with `eviction_sample` left at 1 the backend stays
        bit-identical to pure LRU regardless of this binding."""
        self._popularity = tracker
        self._reland_cost_model = cost_model

    @property
    def total_cost_bytes(self) -> int:
        with self._mu:
            return self._total_cost

    def lookup(
        self, request_keys: Sequence[Key], pod_identifier_set: Set[str]
    ) -> Dict[Key, List[PodEntry]]:
        if not request_keys:
            raise ValueError("no request keys provided for lookup")
        pods_per_key: Dict[Key, List[PodEntry]] = {}
        with self._mu:
            for key in request_keys:
                pod_cache = self._data.get(key)
                if pod_cache is None:
                    return pods_per_key  # gap: post-gap hits can't score
                self._data.move_to_end(key)
                entries = pod_cache.cache.keys()
                if not entries:
                    return pods_per_key  # prefix chain breaks here
                if pod_identifier_set:
                    entries = [
                        e for e in entries
                        if pod_matches(e.pod_identifier, pod_identifier_set)
                    ]
                    if entries:
                        pods_per_key[key] = entries
                else:
                    pods_per_key[key] = entries
        return pods_per_key

    def lookup_many(
        self, requests: Sequence[tuple]
    ) -> List[Dict[Key, List[PodEntry]]]:
        """Batched `lookup` (Index.lookup_many): the global mutex is taken
        ONCE for the whole batch instead of once per item; per-item walk
        semantics (gap cut, filter, recency touch) are the single-call
        path's exactly. Items sharing a key share the entry list object
        (the scorer's batch path reuses weight maps through it)."""
        if not requests:
            return []
        out: List[Dict[Key, List[PodEntry]]] = []
        entries_cache: Dict[Key, list] = {}
        shared: dict = {}
        with self._mu:
            for request_keys, pod_identifier_set in requests:
                if not request_keys:
                    raise ValueError("no request keys provided for lookup")
                pods_per_key: Dict[Key, List[PodEntry]] = {}
                for key in request_keys:
                    pod_cache = self._data.get(key)
                    if pod_cache is None:
                        break  # gap: post-gap hits can't score
                    self._data.move_to_end(key)
                    entries = entries_cache.get(key)
                    if entries is None:
                        entries = entries_cache[key] = pod_cache.cache.keys()
                    if not entries:
                        break  # prefix chain breaks here
                    if pod_identifier_set:
                        sk = (id(pod_identifier_set), key)
                        hits = shared.get(sk)
                        if hits is None:
                            hits = shared[sk] = [
                                e for e in entries
                                if pod_matches(
                                    e.pod_identifier, pod_identifier_set
                                )
                            ]
                        if hits:
                            pods_per_key[key] = hits
                    else:
                        pods_per_key[key] = entries
                out.append(pods_per_key)
        return out

    def add(
        self,
        engine_keys: Sequence[Key],
        request_keys: Sequence[Key],
        entries: Sequence[PodEntry],
    ) -> None:
        if not engine_keys or not request_keys or not entries:
            raise ValueError("no keys or entries provided for adding to index")
        if len(engine_keys) != len(request_keys):
            raise ValueError("engine/request key length mismatch")

        with self._mu:
            for engine_key, request_key in zip(engine_keys, request_keys):
                self._engine_to_request[engine_key] = request_key
                self._request_to_engines.setdefault(request_key, set()).add(engine_key)

                pod_cache = self._data.get(request_key)
                if pod_cache is None:
                    pod_cache = _CostedPodCache(self._pod_cache_size)
                    self._data[request_key] = pod_cache
                else:
                    self._data.move_to_end(request_key)

                self._total_cost -= pod_cache.cost
                with pod_cache.mu:
                    for entry in entries:
                        pod_cache.cache.add(entry, None)
                    pod_cache.cost = calculate_byte_size(
                        request_key, pod_cache.cache.keys()
                    )
                self._total_cost += pod_cache.cost

            # Evict until under budget (LRU, or popularity-weighted within
            # the LRU sample window when a tracker is bound).
            self._evict_over_budget()

    def _evict_over_budget(self) -> None:
        """Shed keys until the byte budget holds (caller holds _mu)."""
        while self._total_cost > self._budget and len(self._data) > 1:
            victim = self._pick_victim()
            evicted_cache = self._data.pop(victim)
            self._total_cost -= evicted_cache.cost
            self._drop_engine_mappings(victim)

    def _pick_victim(self) -> Key:
        """LRU victim, unless a popularity tracker is bound AND the sample
        window is >1: then the lowest-retention key among the
        `eviction_sample` oldest (ties keep LRU order, so a tracker that
        scores everything equally degenerates to exact LRU)."""
        it = iter(self._data)
        oldest = next(it)
        if self._popularity is None or self._eviction_sample <= 1:
            self.eviction_stats["lru"] += 1
            return oldest
        self.eviction_stats["weighted"] += 1
        best_key, best_value = oldest, self._retention(oldest)
        for _ in range(self._eviction_sample - 1):
            key = next(it, None)
            if key is None:
                break
            value = self._retention(key)
            if value < best_value:
                best_key, best_value = key, value
        return best_key

    def _retention(self, key: Key) -> float:
        """Popularity x per-token re-landing cost for one key (see
        bind_popularity)."""
        pop = self._popularity.block_score(key.chunk_hash)
        model = self._reland_cost_model
        if model is None:
            return pop
        pod_cache = self._data[key]
        restorable = any(
            e.device_tier not in ("hbm", "gpu", "device")
            for e in pod_cache.cache.keys()
        )
        reland_s = model.staged_restore_s if restorable else model.recompute_s
        return pop * reland_s

    def evict(self, engine_key: Key, entries: Sequence[PodEntry]) -> None:
        if not entries:
            raise ValueError("no entries provided for eviction from index")
        with self._mu:
            request_key = self._engine_to_request.get(engine_key)
            if request_key is None:
                return
            pod_cache = self._data.get(request_key)
            if pod_cache is None:
                self._engine_to_request.pop(engine_key, None)
                return
            self._total_cost -= pod_cache.cost
            with pod_cache.mu:
                for entry in entries:
                    pod_cache.cache.remove(entry)
                is_empty = len(pod_cache.cache) == 0
                pod_cache.cost = calculate_byte_size(
                    request_key, pod_cache.cache.keys()
                )
            self._total_cost += pod_cache.cost
            if is_empty:
                self._data.pop(request_key, None)
                self._total_cost -= pod_cache.cost
                self._drop_engine_mappings(request_key)

    def get_request_key(self, engine_key: Key) -> Optional[Key]:
        with self._mu:
            return self._engine_to_request.get(engine_key)

    def remove_pod(self, pod_identifier: str) -> int:
        """One-pass quarantine purge (Index.remove_pod contract); the byte
        budget is re-credited as entries leave."""
        target = {pod_identifier}
        removed = 0
        with self._mu:
            for request_key in list(self._data):
                pod_cache = self._data[request_key]
                self._total_cost -= pod_cache.cost
                with pod_cache.mu:
                    victims = [
                        e for e in pod_cache.cache.keys()
                        if pod_matches(e.pod_identifier, target)
                    ]
                    for entry in victims:
                        pod_cache.cache.remove(entry)
                    removed += len(victims)
                    is_empty = len(pod_cache.cache) == 0
                    pod_cache.cost = calculate_byte_size(
                        request_key, pod_cache.cache.keys()
                    )
                self._total_cost += pod_cache.cost
                if is_empty:
                    self._data.pop(request_key, None)
                    self._total_cost -= pod_cache.cost
                    self._drop_engine_mappings(request_key)
        return removed

    def remove_entries(
        self, pod_identifier: str, request_keys, device_tiers=None
    ) -> int:
        """Targeted purge (Index.remove_entries contract); each touched
        key is re-costed and the byte budget re-credited as entries leave
        — a phantom purge frees exactly the budget those entries were
        charged. Plain dict gets, so untouched keys keep recency order."""
        target = {pod_identifier}
        removed = 0
        with self._mu:
            for request_key in request_keys:
                pod_cache = self._data.get(request_key)
                if pod_cache is None:
                    continue
                self._total_cost -= pod_cache.cost
                with pod_cache.mu:
                    victims = [
                        e for e in pod_cache.cache.keys()
                        if pod_matches(e.pod_identifier, target)
                        and (
                            device_tiers is None
                            or e.device_tier in device_tiers
                        )
                    ]
                    for entry in victims:
                        pod_cache.cache.remove(entry)
                    removed += len(victims)
                    is_empty = len(pod_cache.cache) == 0
                    pod_cache.cost = calculate_byte_size(
                        request_key, pod_cache.cache.keys()
                    )
                self._total_cost += pod_cache.cost
                if is_empty:
                    self._data.pop(request_key, None)
                    self._total_cost -= pod_cache.cost
                    self._drop_engine_mappings(request_key)
        return removed

    def export_view(self) -> IndexView:
        """Snapshot oldest-first (Index.export_view contract); cost
        bookkeeping is derived state and is recomputed on import."""
        entries = []
        engine_map = []
        with self._mu:
            for request_key, pod_cache in self._data.items():
                with pod_cache.mu:
                    pods = tuple(
                        (e.pod_identifier, e.device_tier)
                        for e in pod_cache.cache.keys()
                    )
                entries.append(
                    (request_key.model_name, request_key.chunk_hash, pods)
                )
            engine_map = [
                (ek.model_name, ek.chunk_hash, rk.model_name, rk.chunk_hash)
                for ek, rk in self._engine_to_request.items()
            ]
        return IndexView(entries=entries, engine_map=engine_map)

    def import_view(self, view: IndexView) -> int:
        """Rebuild in view order, recosting each key and re-running the
        byte-budget eviction sweep at the end (Index.import_view) — a
        snapshot from a larger-budget replica imports to the newest
        entries that fit, not over budget."""
        imported = 0
        with self._mu:
            for model_name, chunk_hash, pods in view.entries:
                request_key = Key(model_name, chunk_hash)
                pod_cache = self._data.get(request_key)
                if pod_cache is None:
                    pod_cache = _CostedPodCache(self._pod_cache_size)
                    self._data[request_key] = pod_cache
                else:
                    self._data.move_to_end(request_key)
                self._total_cost -= pod_cache.cost
                with pod_cache.mu:
                    for pod, tier in pods:
                        pod_cache.cache.add(PodEntry(pod, tier), None)
                        imported += 1
                    pod_cache.cost = calculate_byte_size(
                        request_key, pod_cache.cache.keys()
                    )
                self._total_cost += pod_cache.cost
            for engine_model, engine_hash, req_model, req_hash in view.engine_map:
                engine_key = Key(engine_model, engine_hash)
                request_key = Key(req_model, req_hash)
                self._engine_to_request[engine_key] = request_key
                self._request_to_engines.setdefault(request_key, set()).add(
                    engine_key
                )
            self._evict_over_budget()
        return imported

    def _drop_engine_mappings(self, request_key: Key) -> None:
        for engine_key in self._request_to_engines.pop(request_key, ()):  # noqa: B020
            self._engine_to_request.pop(engine_key, None)

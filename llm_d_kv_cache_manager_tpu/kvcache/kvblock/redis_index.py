"""Distributed KV-block index on Redis/Valkey.

Parity target: RedisIndex (/root/reference/pkg/kvcache/kvblock/redis.go):
the index shared by multiple indexer replicas. Schema:

- hash per request key (`<model>@<decimal-hash>`), one field per pod entry
  (`pod@tier`, empty value),
- string key `engine:<model>@<hash>` → request-key string for the
  engine→request mapping.

Lookups pipeline one HKEYS per block key (single RTT); a missing key or a
fully-filtered-out key cuts the prefix walk, matching redis.go:179-205.
Valkey URLs (valkey://) are accepted and rewritten; the reference's RDMA
placeholder maps to DCN-attached Valkey on TPU fleets (config flag kept).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import Index
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import Key, PodEntry, pod_matches
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.resp import (
    RespConnection,
    RespError,
)
from llm_d_kv_cache_manager_tpu.utils import logging as kvlog

logger = kvlog.get_logger("kvblock.redis")


@dataclass
class RedisIndexConfig:
    url: str = "redis://localhost:6379"
    timeout_s: float = 5.0
    enable_rdma: bool = False  # Valkey-over-DCN placeholder (reference parity)


def _key_str(key: Key) -> str:
    return f"{key.model_name}@{key.chunk_hash:d}"


def _engine_key_str(key: Key) -> str:
    return "engine:" + _key_str(key)


def _parse_key(text: str) -> Optional[Key]:
    model, sep, hash_str = text.rpartition("@")
    if not sep or not hash_str.isdigit():
        return None
    return Key(model, int(hash_str))


def _parse_entry(field: str) -> Optional[PodEntry]:
    # rpartition: the tier is always the LAST segment, so ranked pod
    # identities ("pod@dp0@hbm") round-trip with their rank intact.
    pod, sep, tier = field.rpartition("@")
    if not sep:
        return None
    return PodEntry(pod, tier)


# After a failed reconnect, skip further reconnect attempts for this long:
# without it, a partitioned Redis makes EVERY scoring lookup block the full
# connect timeout before soft-failing — a fleet-wide stall, not a miss.
RECONNECT_BACKOFF_S = 5.0
# Cut-chain events surface at WARNING at most this often (an outage must be
# operator-visible, not a debug-level mystery hit-rate collapse).
_WARN_INTERVAL_S = 30.0


class RedisIndex(Index):
    def __init__(self, config: Optional[RedisIndexConfig] = None):
        self.config = config or RedisIndexConfig()
        self._conn = RespConnection(self.config.url, self.config.timeout_s)
        self._mu = threading.Lock()  # guards backoff/reconnect bookkeeping
        self._reconnecting = False
        self._down_until = 0.0
        # Negative sentinel: monotonic() is time-since-boot, so 0.0 would
        # suppress the FIRST outage warning during early uptime.
        self._last_warn = -_WARN_INTERVAL_S
        self._conn.connect()
        if not self._conn.ping():
            raise ConnectionError(f"redis PING failed for {self.config.url}")

    def close(self) -> None:
        self._conn.close()

    def _pipeline(self, commands):
        # ADVICE r2: _down_until (and _reconnecting/_last_warn) are only
        # read/written under _mu — with the threaded scoring pool,
        # unguarded reads let concurrent lookups race the backoff window
        # and each pay a full connect timeout. _mu is NEVER held across
        # socket I/O: exactly one thread claims the reconnect (flag below)
        # and pays the connect timeout while every other thread fails fast
        # to cache-miss degradation.
        with self._mu:
            if time.monotonic() < self._down_until or self._reconnecting:
                raise ConnectionError(
                    f"redis backend in reconnect backoff ({self.config.url})"
                )
        try:
            return self._conn.pipeline(commands)
        except OSError:
            with self._mu:
                if time.monotonic() < self._down_until or self._reconnecting:
                    raise  # another thread is on it / already failed
                self._reconnecting = True
            try:
                self._conn.connect()
                replies = self._conn.pipeline(commands)
            except OSError:
                with self._mu:
                    self._down_until = time.monotonic() + RECONNECT_BACKOFF_S
                raise
            finally:
                with self._mu:
                    self._reconnecting = False
            with self._mu:
                self._down_until = 0.0
            return replies

    def _warn_cut(self, e: Exception) -> None:
        now = time.monotonic()
        with self._mu:
            if now - self._last_warn < _WARN_INTERVAL_S:
                return
            self._last_warn = now
        logger.warning(
            "redis index unavailable, scoring degrades to cache misses: %s", e
        )

    def lookup(
        self, request_keys: Sequence[Key], pod_identifier_set: Set[str]
    ) -> Dict[Key, List[PodEntry]]:
        if not request_keys:
            raise ValueError("no request keys provided for lookup")

        try:
            replies = self._pipeline(
                [("HKEYS", _key_str(k)) for k in request_keys]
            )
        except OSError as e:  # includes ConnectionError
            # Reference semantics (redis.go:185-192): a Redis failure cuts
            # the prefix chain — the read path degrades to a cache miss, it
            # never unwinds the scoring request. Writes still raise (their
            # callers log and drop the event).
            self._warn_cut(e)
            return {}

        pods_per_key: Dict[Key, List[PodEntry]] = {}
        for key, reply in zip(request_keys, replies):
            if isinstance(reply, RespError) or reply is None:
                logger.debug("lookup reply error for %s: %s", key, reply)
                return pods_per_key  # cut: prefix chain breaks here
            entries: List[PodEntry] = []
            for field in reply:
                entry = _parse_entry(
                    field.decode("utf-8") if isinstance(field, bytes) else field
                )
                if entry is None:
                    continue
                if not pod_identifier_set or pod_matches(
                    entry.pod_identifier, pod_identifier_set
                ):
                    entries.append(entry)
            if not entries:
                return pods_per_key  # cut on miss or fully-filtered key
            pods_per_key[key] = entries
        return pods_per_key

    def add(
        self,
        engine_keys: Sequence[Key],
        request_keys: Sequence[Key],
        entries: Sequence[PodEntry],
    ) -> None:
        if not engine_keys or not request_keys or not entries:
            raise ValueError("no keys or entries provided for adding to index")
        if len(engine_keys) != len(request_keys):
            raise ValueError("engine/request key length mismatch")

        commands = []
        for engine_key, request_key in zip(engine_keys, request_keys):
            commands.append(("SET", _engine_key_str(engine_key), _key_str(request_key)))
            for entry in entries:
                commands.append(("HSET", _key_str(request_key), str(entry), ""))
        self._pipeline(commands)

    def evict(self, engine_key: Key, entries: Sequence[PodEntry]) -> None:
        if not entries:
            raise ValueError("no entries provided for eviction from index")
        request_key = self.get_request_key(engine_key)
        if request_key is None:
            return
        commands = [("HDEL", _key_str(request_key), str(e)) for e in entries]
        commands.append(("HLEN", _key_str(request_key)))
        replies = self._pipeline(commands)
        if replies and replies[-1] == 0:
            self._pipeline([
                ("DEL", _key_str(request_key)),
                ("DEL", _engine_key_str(engine_key)),
            ])

    def get_request_key(self, engine_key: Key) -> Optional[Key]:
        # Deliberately NOT soft-failed: None means "parent genuinely not
        # indexed" and makes the event pool start a fresh hash chain —
        # returning it on a connection blip would commit mid-prompt blocks
        # under fresh-chain request keys (false prefix hits that persist).
        # A raised error instead drops the event batch (worker catch-all),
        # which is consistent.
        replies = self._pipeline([("GET", _engine_key_str(engine_key))])
        value = replies[0]
        if value is None or isinstance(value, RespError):
            return None
        return _parse_key(value.decode("utf-8") if isinstance(value, bytes) else value)

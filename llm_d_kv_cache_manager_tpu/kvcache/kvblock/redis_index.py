"""Distributed KV-block index on Redis/Valkey.

Parity target: RedisIndex (/root/reference/pkg/kvcache/kvblock/redis.go):
the index shared by multiple indexer replicas. Schema:

- hash per request key (`<model>@<decimal-hash>`), one field per pod entry
  (`pod@tier`, empty value),
- string key `engine:<model>@<hash>` → request-key string for the
  engine→request mapping.

Lookups pipeline one HKEYS per block key (single RTT); a missing key or a
fully-filtered-out key cuts the prefix walk, matching redis.go:179-205.
Valkey URLs (valkey://) are accepted and rewritten; the reference's RDMA
placeholder maps to DCN-attached Valkey on TPU fleets (config flag kept).
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import Index, IndexView
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import Key, PodEntry, pod_matches
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.resp import (
    RespConnection,
    RespError,
)
from llm_d_kv_cache_manager_tpu.utils import logging as kvlog

logger = kvlog.get_logger("kvblock.redis")


@dataclass
class RedisIndexConfig:
    url: str = "redis://localhost:6379"
    timeout_s: float = 5.0
    enable_rdma: bool = False  # Valkey-over-DCN placeholder (reference parity)
    # Reconnect backoff (after a failed reconnect, lookups fail fast for
    # this long instead of each paying the connect timeout). Consecutive
    # failures double the window up to the cap; jitter (a uniform fraction
    # of the window) desynchronizes a fleet of manager replicas all
    # backing off from the same outage.
    reconnect_backoff_s: float = 5.0
    reconnect_backoff_max_s: float = 60.0
    reconnect_jitter: float = 0.2
    # SCAN page size for bulk maintenance passes (remove_pod).
    scan_count: int = 512


def _key_str(key: Key) -> str:
    return f"{key.model_name}@{key.chunk_hash:d}"


def _engine_key_str(key: Key) -> str:
    return "engine:" + _key_str(key)


def _parse_key(text: str) -> Optional[Key]:
    model, sep, hash_str = text.rpartition("@")
    if not sep or not hash_str.isdigit():
        return None
    return Key(model, int(hash_str))


def _parse_entry(field: str) -> Optional[PodEntry]:
    # rpartition: the tier is always the LAST segment, so ranked pod
    # identities ("pod@dp0@hbm") round-trip with their rank intact.
    pod, sep, tier = field.rpartition("@")
    if not sep:
        return None
    return PodEntry(pod, tier)


# Default for RedisIndexConfig.reconnect_backoff_s (kept as a module
# constant for back-compat with callers/tests that monkeypatch it): after a
# failed reconnect, skip further reconnect attempts for this long — without
# it, a partitioned Redis makes EVERY scoring lookup block the full connect
# timeout before soft-failing — a fleet-wide stall, not a miss.
RECONNECT_BACKOFF_S = 5.0
# Cut-chain events surface at WARNING at most this often (an outage must be
# operator-visible, not a debug-level mystery hit-rate collapse).
_WARN_INTERVAL_S = 30.0


class RedisIndex(Index):
    def __init__(self, config: Optional[RedisIndexConfig] = None):
        self.config = config or RedisIndexConfig()
        self._conn = RespConnection(self.config.url, self.config.timeout_s)
        self._mu = threading.Lock()  # guards backoff/reconnect bookkeeping
        self._reconnecting = False
        self._down_until = 0.0
        # Connection lifecycle: "up" -> "down" (first pipeline failure) ->
        # "backoff" (reconnect failed; lookups fail fast) -> "up". Every
        # transition is logged and counted
        # (kvcache_redis_state_transitions_total) — an outage must be
        # operator-visible, not a silently-absorbed hit-rate collapse.
        self._state = "up"
        self._consecutive_failures = 0
        self._jitter_rng = random.Random()
        # Negative sentinel: monotonic() is time-since-boot, so 0.0 would
        # suppress the FIRST outage warning during early uptime.
        self._last_warn = -_WARN_INTERVAL_S
        self._conn.connect()
        if not self._conn.ping():
            raise ConnectionError(f"redis PING failed for {self.config.url}")

    def close(self) -> None:
        self._conn.close()

    def _pipeline(self, commands):
        # ADVICE r2: _down_until (and _reconnecting/_last_warn) are only
        # read/written under _mu — with the threaded scoring pool,
        # unguarded reads let concurrent lookups race the backoff window
        # and each pay a full connect timeout. _mu is NEVER held across
        # socket I/O: exactly one thread claims the reconnect (flag below)
        # and pays the connect timeout while every other thread fails fast
        # to cache-miss degradation.
        with self._mu:
            if time.monotonic() < self._down_until or self._reconnecting:
                raise ConnectionError(
                    f"redis backend in reconnect backoff ({self.config.url})"
                )
        try:
            return self._conn.pipeline(commands)
        except OSError:
            with self._mu:
                if time.monotonic() < self._down_until or self._reconnecting:
                    raise  # another thread is on it / already failed
                self._reconnecting = True
                self._set_state_locked("down")
            try:
                self._conn.connect()
                replies = self._conn.pipeline(commands)
            except OSError:
                with self._mu:
                    delay = self._backoff_delay_locked()
                    self._down_until = time.monotonic() + delay
                    self._set_state_locked("backoff")
                logger.warning(
                    "redis reconnect to %s failed (attempt %d): backing off "
                    "%.2fs", self.config.url, self._consecutive_failures, delay,
                )
                raise
            finally:
                with self._mu:
                    self._reconnecting = False
            with self._mu:
                self._down_until = 0.0
                self._consecutive_failures = 0
                self._set_state_locked("up")
            return replies

    def _backoff_delay_locked(self) -> float:
        """Next capped-exponential backoff window (+jitter). Holds `_mu`."""
        self._consecutive_failures += 1
        base = max(self.config.reconnect_backoff_s, 0.0)
        delay = min(
            base * (2.0 ** (self._consecutive_failures - 1)),
            max(self.config.reconnect_backoff_max_s, base),
        )
        jitter = max(self.config.reconnect_jitter, 0.0)
        if jitter:
            delay *= 1.0 + jitter * self._jitter_rng.random()
        return delay

    def _set_state_locked(self, state: str) -> None:
        if state == self._state:
            return
        old, self._state = self._state, state
        from llm_d_kv_cache_manager_tpu.metrics import collector as metrics

        metrics.count_redis_transition(state)
        log = logger.info if state == "up" else logger.warning
        log("redis index %s: %s -> %s", self.config.url, old, state)

    def _warn_cut(self, e: Exception) -> None:
        now = time.monotonic()
        with self._mu:
            if now - self._last_warn < _WARN_INTERVAL_S:
                return
            self._last_warn = now
        logger.warning(
            "redis index unavailable, scoring degrades to cache misses: %s", e
        )

    def lookup(
        self, request_keys: Sequence[Key], pod_identifier_set: Set[str]
    ) -> Dict[Key, List[PodEntry]]:
        if not request_keys:
            raise ValueError("no request keys provided for lookup")

        try:
            replies = self._pipeline(
                [("HKEYS", _key_str(k)) for k in request_keys]
            )
        except OSError as e:  # includes ConnectionError
            # Reference semantics (redis.go:185-192): a Redis failure cuts
            # the prefix chain — the read path degrades to a cache miss, it
            # never unwinds the scoring request. Writes still raise (their
            # callers log and drop the event).
            self._warn_cut(e)
            return {}

        pods_per_key: Dict[Key, List[PodEntry]] = {}
        for key, reply in zip(request_keys, replies):
            if isinstance(reply, RespError) or reply is None:
                logger.debug("lookup reply error for %s: %s", key, reply)
                return pods_per_key  # cut: prefix chain breaks here
            entries: List[PodEntry] = []
            for field in reply:
                entry = _parse_entry(
                    field.decode("utf-8") if isinstance(field, bytes) else field
                )
                if entry is None:
                    continue
                if not pod_identifier_set or pod_matches(
                    entry.pod_identifier, pod_identifier_set
                ):
                    entries.append(entry)
            if not entries:
                return pods_per_key  # cut on miss or fully-filtered key
            pods_per_key[key] = entries
        return pods_per_key

    def lookup_many(
        self, requests: Sequence[tuple]
    ) -> List[Dict[Key, List[PodEntry]]]:
        """Batched `lookup` (Index.lookup_many): ONE pipelined round trip
        covers the union of every item's keys — a 32-request batch over a
        shared prefix pays one network RTT instead of 32 — then each item
        walks the shared parsed replies with the single-call cut semantics
        (a miss, an error reply, or a fully-filtered key cuts that item's
        chain, exactly as in `lookup`). A Redis outage degrades the whole
        batch to cache misses, never an exception."""
        if not requests:
            return []
        unique: List[Key] = []
        seen = set()
        for keys, _ in requests:
            if not keys:
                raise ValueError("no request keys provided for lookup")
            for k in keys:
                if k not in seen:
                    seen.add(k)
                    unique.append(k)
        try:
            replies = self._pipeline(
                [("HKEYS", _key_str(k)) for k in unique]
            )
        except OSError as e:  # includes ConnectionError
            self._warn_cut(e)
            return [{} for _ in requests]

        parsed: Dict[Key, Optional[List[PodEntry]]] = {}
        for key, reply in zip(unique, replies):
            if isinstance(reply, RespError) or reply is None:
                logger.debug("lookup reply error for %s: %s", key, reply)
                parsed[key] = None
                continue
            entries: List[PodEntry] = []
            for field in reply:
                entry = _parse_entry(
                    field.decode("utf-8") if isinstance(field, bytes) else field
                )
                if entry is not None:
                    entries.append(entry)
            parsed[key] = entries

        out: List[Dict[Key, List[PodEntry]]] = []
        shared: dict = {}
        for request_keys, pod_identifier_set in requests:
            pods_per_key: Dict[Key, List[PodEntry]] = {}
            for key in request_keys:
                entries = parsed.get(key)
                if entries is None:
                    break  # error reply: prefix chain breaks here
                if pod_identifier_set:
                    sk = (id(pod_identifier_set), key)
                    hits = shared.get(sk)
                    if hits is None:
                        hits = shared[sk] = [
                            e for e in entries
                            if pod_matches(e.pod_identifier, pod_identifier_set)
                        ]
                else:
                    hits = entries
                if not hits:
                    break  # cut on miss or fully-filtered key
                pods_per_key[key] = hits
            out.append(pods_per_key)
        return out

    def add(
        self,
        engine_keys: Sequence[Key],
        request_keys: Sequence[Key],
        entries: Sequence[PodEntry],
    ) -> None:
        if not engine_keys or not request_keys or not entries:
            raise ValueError("no keys or entries provided for adding to index")
        if len(engine_keys) != len(request_keys):
            raise ValueError("engine/request key length mismatch")

        commands = []
        for engine_key, request_key in zip(engine_keys, request_keys):
            commands.append(("SET", _engine_key_str(engine_key), _key_str(request_key)))
            for entry in entries:
                commands.append(("HSET", _key_str(request_key), str(entry), ""))
        self._pipeline(commands)

    def evict(self, engine_key: Key, entries: Sequence[PodEntry]) -> None:
        if not entries:
            raise ValueError("no entries provided for eviction from index")
        request_key = self.get_request_key(engine_key)
        if request_key is None:
            return
        commands = [("HDEL", _key_str(request_key), str(e)) for e in entries]
        commands.append(("HLEN", _key_str(request_key)))
        replies = self._pipeline(commands)
        if replies and replies[-1] == 0:
            self._pipeline([
                ("DEL", _key_str(request_key)),
                ("DEL", _engine_key_str(engine_key)),
            ])

    def get_request_key(self, engine_key: Key) -> Optional[Key]:
        # Deliberately NOT soft-failed: None means "parent genuinely not
        # indexed" and makes the event pool start a fresh hash chain —
        # returning it on a connection blip would commit mid-prompt blocks
        # under fresh-chain request keys (false prefix hits that persist).
        # A raised error instead drops the event batch (worker catch-all),
        # which is consistent.
        replies = self._pipeline([("GET", _engine_key_str(engine_key))])
        value = replies[0]
        if value is None or isinstance(value, RespError):
            return None
        return _parse_key(value.decode("utf-8") if isinstance(value, bytes) else value)

    def remove_pod(self, pod_identifier: str) -> int:
        """One-pass quarantine purge (Index.remove_pod contract).

        SCAN-walks the keyspace (cursor iteration, never the blocking
        KEYS), HDELs the pod's fields from each request-key hash in
        pipelined pages, DELs hashes that emptied, and finally drops
        engine:* mappings that point at deleted request keys. Connection
        errors propagate like the write path's (callers log and retry the
        quarantine; the pod stays excluded by health state meanwhile).
        """
        target = {pod_identifier}
        removed = 0
        emptied: Set[str] = set()
        for page in self._scan_pages():
            request_keys = [k for k in page if not k.startswith("engine:")]
            if not request_keys:
                continue
            replies = self._pipeline([("HKEYS", k) for k in request_keys])
            commands = []
            victims_per_key: List[tuple] = []
            for key_str, reply in zip(request_keys, replies):
                if isinstance(reply, RespError) or reply is None:
                    continue
                victims = []
                for field in reply:
                    field_str = (
                        field.decode("utf-8") if isinstance(field, bytes) else field
                    )
                    entry = _parse_entry(field_str)
                    if entry is not None and pod_matches(
                        entry.pod_identifier, target
                    ):
                        victims.append(field_str)
                if victims:
                    commands.append(("HDEL", key_str, *victims))
                    commands.append(("HLEN", key_str))
                    victims_per_key.append((key_str, len(victims)))
            if not commands:
                continue
            replies = self._pipeline(commands)
            del_cmds = []
            for i, (key_str, n_victims) in enumerate(victims_per_key):
                removed += n_victims
                if replies[2 * i + 1] == 0:  # the HLEN after the HDEL
                    del_cmds.append(("DEL", key_str))
                    emptied.add(key_str)
            if del_cmds:
                self._pipeline(del_cmds)
        if emptied:
            for page in self._scan_pages(match="engine:*"):
                engine_keys = [k for k in page if k.startswith("engine:")]
                if not engine_keys:
                    continue
                values = self._pipeline([("GET", k) for k in engine_keys])
                stale = [
                    k
                    for k, v in zip(engine_keys, values)
                    if isinstance(v, (bytes, str))
                    and (v.decode("utf-8") if isinstance(v, bytes) else v)
                    in emptied
                ]
                if stale:
                    self._pipeline([("DEL", *stale)])
        return removed

    def remove_entries(
        self, pod_identifier: str, request_keys, device_tiers=None
    ) -> int:
        """Targeted purge (Index.remove_entries contract), fully
        pipelined: ONE round trip reads the targeted hashes (HKEYS per
        key), one issues the HDELs + HLENs, and one DELs the keys that
        emptied — no SCAN of the keyspace (that is `remove_pod`'s job; a
        feedback purge must stay O(targeted keys), not O(index)).
        Engine:* mappings pointing at deleted keys are dropped in a final
        targeted sweep over the emptied keys' engine aliases resolved the
        same way `evict` resolves them — by skipping it: a dangling
        engine:* row is self-healing here (get_request_key → evict finds
        the hash gone and deletes the row), and hunting it down would
        cost the SCAN this method exists to avoid. Connection errors
        propagate like the write path's."""
        target = {pod_identifier}
        keys = list(request_keys)
        if not keys:
            return 0
        replies = self._pipeline([("HKEYS", _key_str(k)) for k in keys])
        commands = []
        victims_per_key: List[tuple] = []
        for key, reply in zip(keys, replies):
            if isinstance(reply, RespError) or reply is None:
                continue
            victims = []
            for field in reply:
                field_str = (
                    field.decode("utf-8") if isinstance(field, bytes) else field
                )
                entry = _parse_entry(field_str)
                if (
                    entry is not None
                    and pod_matches(entry.pod_identifier, target)
                    and (
                        device_tiers is None
                        or entry.device_tier in device_tiers
                    )
                ):
                    victims.append(field_str)
            if victims:
                key_str = _key_str(key)
                commands.append(("HDEL", key_str, *victims))
                commands.append(("HLEN", key_str))
                victims_per_key.append((key, len(victims)))
        if not commands:
            return 0
        replies = self._pipeline(commands)
        removed = 0
        del_cmds = []
        for i, (key, n_victims) in enumerate(victims_per_key):
            removed += n_victims
            if replies[2 * i + 1] == 0:  # the HLEN after the HDEL
                del_cmds.append(("DEL", _key_str(key)))
                # Engine aliases resolve through the same decimal-hash
                # string on this backend's schema, so the 1:1 alias row
                # can be dropped in the same sweep.
                del_cmds.append(("DEL", _engine_key_str(key)))
        if del_cmds:
            self._pipeline(del_cmds)
        return removed

    def export_view(self) -> IndexView:
        """SCAN-walk the keyspace into an IndexView (Index.export_view).

        Redis has no recency order to preserve — rows come out in SCAN
        order, which is fine: restores into redis are order-insensitive,
        and restores into LRU backends get an arbitrary-but-valid recency
        seed. Connection errors propagate (a snapshot must be complete or
        fail loudly, never silently partial)."""
        entries = []
        engine_map = []
        for page in self._scan_pages():
            request_strs = [k for k in page if not k.startswith("engine:")]
            engine_strs = [k for k in page if k.startswith("engine:")]
            if request_strs:
                replies = self._pipeline([("HKEYS", k) for k in request_strs])
                for key_str, reply in zip(request_strs, replies):
                    key = _parse_key(key_str)
                    if key is None or isinstance(reply, RespError) or reply is None:
                        continue
                    pods = []
                    for field in reply:
                        entry = _parse_entry(
                            field.decode("utf-8")
                            if isinstance(field, bytes) else field
                        )
                        if entry is not None:
                            pods.append((entry.pod_identifier, entry.device_tier))
                    entries.append((key.model_name, key.chunk_hash, tuple(pods)))
            if engine_strs:
                values = self._pipeline([("GET", k) for k in engine_strs])
                for key_str, value in zip(engine_strs, values):
                    if value is None or isinstance(value, RespError):
                        continue
                    engine_key = _parse_key(key_str[len("engine:"):])
                    request_key = _parse_key(
                        value.decode("utf-8") if isinstance(value, bytes) else value
                    )
                    if engine_key is None or request_key is None:
                        continue
                    engine_map.append((
                        engine_key.model_name, engine_key.chunk_hash,
                        request_key.model_name, request_key.chunk_hash,
                    ))
        return IndexView(entries=entries, engine_map=engine_map)

    def import_view(self, view: IndexView) -> int:
        """Pipelined HSET/SET restore (Index.import_view). Batched in
        pages so a large snapshot doesn't build one giant pipeline."""
        imported = 0
        commands = []
        for model_name, chunk_hash, pods in view.entries:
            key_str = _key_str(Key(model_name, chunk_hash))
            for pod, tier in pods:
                commands.append(
                    ("HSET", key_str, str(PodEntry(pod, tier)), "")
                )
                imported += 1
        for engine_model, engine_hash, req_model, req_hash in view.engine_map:
            commands.append((
                "SET", _engine_key_str(Key(engine_model, engine_hash)),
                _key_str(Key(req_model, req_hash)),
            ))
        for i in range(0, len(commands), 1024):
            self._pipeline(commands[i:i + 1024])
        return imported

    def _scan_pages(self, match: str = "*"):
        """Yield pages of keys (decoded str) via cursor SCAN."""
        cursor = "0"
        while True:
            reply = self._pipeline(
                [("SCAN", cursor, "MATCH", match, "COUNT", self.config.scan_count)]
            )[0]
            if isinstance(reply, RespError) or reply is None:
                return
            cursor_raw, keys = reply[0], reply[1]
            cursor = (
                cursor_raw.decode("utf-8")
                if isinstance(cursor_raw, bytes)
                else str(cursor_raw)
            )
            yield [
                k.decode("utf-8") if isinstance(k, bytes) else k for k in keys
            ]
            if cursor == "0":
                return

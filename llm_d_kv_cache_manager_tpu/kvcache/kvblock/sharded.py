"""Sharded, lock-striped KV-block index with a lock-free read view.

The seed `InMemoryIndex` funnels every reader and every kvevents shard worker
through one global `LRUCache` lock, takes it once *per key* (a 128-block
lookup = 128 acquisitions), and mutates recency order on the read path — so
concurrent `GetPodScores` calls serialize against each other *and* against
the write plane. This backend splits the index into S independent segments
and projects them into a read-optimized view:

- **Striping.** A key routes to segment `chunk_hash % S`. The chunk hash is
  itself an FNV-64a chain hash (hashing.py) — the same hash family the
  kvevents pool shards messages with (`fnv32a(pod) % S`,
  `kvevents/pool.py:add_task`) — so writer→segment affinity costs a single
  integer mod, no per-key re-hashing. Engine keys stripe the engine→request
  map the same way; `evict` resolves the engine segment first, then operates
  on the request key's segment (the two may differ — each step locks only
  its own stripe). Capacity is enforced per segment at ceil(size / S): the
  same total bound, striped.
- **Batching.** Write-side operations group keys by segment first and use
  the batched `LRUCache.get_many/peek_many/add_many` primitives: one lock
  acquisition per *touched segment* per call instead of one per key.
- **Read-mostly fast path (`touch=False`).** Each per-key pod LRU publishes
  its entries as an immutable tuple after every mutation, and the index
  maintains `_view: {request_key: entries}` — plain dict ops, atomic under
  the GIL. `lookup` walks the view with **zero lock acquisitions**: reads
  stop serializing on the write plane entirely. The price is recency: plain
  lookups don't refresh LRU order, so every `recency_refresh_interval`-th
  lookup call runs a batched `get_many` touch pass (one lock per touched
  segment) to keep hot chains away from the eviction end. Interval 1 =
  touch every lookup (the seed's recency behavior).

Per-segment semantics are the seed's exactly (in_memory.py): empty-pod-cache
and missing-key both cut the lookup walk, double-checked insert on add,
evict re-checks emptiness before removing the key. View maintenance is
write-side: entries are republished under the pod cache's mutex (so
last-writer-wins matches the pod LRU's state) and capacity evictions prune
the view through the segment LRU's eviction callback; adders re-check
membership after publishing so an interleaved eviction can't resurrect a
dead view entry.
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
    DEFAULT_INDEX_SIZE,
    DEFAULT_PODS_PER_KEY,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import Index, IndexView
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import Key, PodEntry, pod_matches
from llm_d_kv_cache_manager_tpu.utils.lru import LRUCache
from llm_d_kv_cache_manager_tpu.utils import logging as kvlog

logger = kvlog.get_logger("kvblock.sharded")

DEFAULT_NUM_SHARDS = 16
DEFAULT_RECENCY_REFRESH = 64


@dataclass
class ShardedIndexConfig:
    size: int = DEFAULT_INDEX_SIZE
    pod_cache_size: int = DEFAULT_PODS_PER_KEY
    num_shards: int = DEFAULT_NUM_SHARDS
    # One lookup call out of this many runs the batched recency touch pass;
    # the rest read the lock-free view. <=1 = touch every lookup (seed
    # behavior).
    recency_refresh_interval: int = DEFAULT_RECENCY_REFRESH


class _ShardPodCache:
    """Per-key pod LRU with a published read snapshot.

    `entries` is a tuple republished (whole-object swap, atomic under the
    GIL) after every mutation batch, in the pod LRU's oldest-first order —
    exactly what `LRUCache.keys()` returns in the seed.
    """

    __slots__ = ("cache", "mu", "entries")

    def __init__(self, capacity: int):
        self.cache: LRUCache[PodEntry, None] = LRUCache(capacity)
        self.mu = threading.Lock()
        self.entries: tuple = ()

    def republish(self) -> None:
        """Call with `mu` held after mutating `cache`."""
        self.entries = tuple(self.cache.keys())


class _Segment:
    """One lock stripe: a two-level LRU plus its slice of the engine map."""

    __slots__ = ("data", "engine_to_request")

    def __init__(self, capacity: int, on_evict):
        self.data: LRUCache[Key, _ShardPodCache] = LRUCache(
            capacity, on_evict=on_evict
        )
        self.engine_to_request: LRUCache[Key, Key] = LRUCache(capacity)


class ShardedIndex(Index):
    def __init__(self, config: Optional[ShardedIndexConfig] = None):
        cfg = config or ShardedIndexConfig()
        if cfg.num_shards <= 0:
            raise ValueError(f"num_shards must be positive, got {cfg.num_shards}")
        if cfg.size <= 0:
            raise ValueError(f"index size must be positive, got {cfg.size}")
        self._num_shards = cfg.num_shards
        self._pod_cache_size = cfg.pod_cache_size
        self._refresh = cfg.recency_refresh_interval
        self._per_shard_capacity = max(1, -(-cfg.size // cfg.num_shards))  # ceil
        # Lock-free read view: {request_key: published entries tuple}.
        # Single-op dict reads/writes are GIL-atomic; the segment LRU prunes
        # it on capacity eviction via the on_evict hook (runs under the
        # segment lock, so a pop can't interleave mid-eviction).
        self._view: Dict[Key, tuple] = {}
        # Monotonic count of keys leaving any segment's data LRU. Writers
        # snapshot it around a publish batch: unchanged means no eviction
        # could have raced their view writes, so the common (far-below-
        # capacity) add path skips the membership re-check entirely.
        self._evictions = 0
        self._segments = [
            _Segment(self._per_shard_capacity, self._on_data_evict)
            for _ in range(cfg.num_shards)
        ]
        # Starts at 1 so the refresh is periodic (every Nth lookup), not
        # immediate-then-periodic. itertools.count is GIL-thread-safe.
        self._lookup_tick = itertools.count(1)

    def _on_data_evict(self, key: Key, pod_cache) -> None:
        # Runs under the evicting segment's lock. The lost-increment race
        # between segments is harmless: the counter is only compared for
        # change, never for magnitude, and it never goes backwards.
        self._evictions += 1
        self._view.pop(key, None)

    # -- sharding ----------------------------------------------------------

    @property
    def num_shards(self) -> int:
        return self._num_shards

    @property
    def per_shard_capacity(self) -> int:
        return self._per_shard_capacity

    def shard_of(self, key: Key) -> int:
        """Deterministic stripe for a key: its FNV-64a chunk hash mod S."""
        return key.chunk_hash % self._num_shards

    def segment_sizes(self) -> List[int]:
        """Current entry count per segment (capacity-invariant probes)."""
        return [len(seg.data) for seg in self._segments]

    def _group_by_shard(self, keys: Sequence[Key]):
        """(shard, keys) pairs for the non-empty stripes."""
        n = self._num_shards
        grouped: List[Optional[List[Key]]] = [None] * n
        for key in keys:
            shard = key.chunk_hash % n
            bucket = grouped[shard]
            if bucket is None:
                grouped[shard] = [key]
            else:
                bucket.append(key)
        return [(s, b) for s, b in enumerate(grouped) if b is not None]

    # -- Index contract ----------------------------------------------------

    def lookup(
        self, request_keys: Sequence[Key], pod_identifier_set: Set[str]
    ) -> Dict[Key, List[PodEntry]]:
        if not request_keys:
            raise ValueError("no request keys provided for lookup")

        refresh = self._refresh
        if refresh <= 1 or next(self._lookup_tick) % refresh == 0:
            # Periodic recency refresh: one batched get_many per touched
            # segment moves this chain away from the LRU eviction end.
            for shard, keys in self._group_by_shard(request_keys):
                self._segments[shard].data.get_many(keys)

        # Lock-free walk in prompt order with the seed's cut semantics: a
        # missing key (view miss) and a present-but-podless key (empty
        # published tuple) both end the search — the scorer's active set
        # empties at any gap, so post-gap hits can't score.
        view_get = self._view.get
        pods_per_key: Dict[Key, List[PodEntry]] = {}
        if pod_identifier_set:
            for key in request_keys:
                entries = view_get(key)
                if not entries:
                    kvlog.trace(logger, "chain cut at key: %s", key)
                    return pods_per_key
                hits = [
                    e for e in entries
                    if pod_matches(e.pod_identifier, pod_identifier_set)
                ]
                if hits:
                    pods_per_key[key] = hits
        else:
            for key in request_keys:
                entries = view_get(key)
                if not entries:
                    kvlog.trace(logger, "chain cut at key: %s", key)
                    return pods_per_key
                pods_per_key[key] = list(entries)
        return pods_per_key

    def lookup_many(
        self, requests: Sequence[tuple]
    ) -> List[Dict[Key, List[PodEntry]]]:
        """Batched `lookup` (Index.lookup_many): the whole batch's recency
        refresh collapses into at most ONE `get_many` per touched segment
        — each stripe lock is crossed once per batch, not once per item —
        and the per-item walks stay lock-free on the published view.

        Within a batch, items sharing a key (and pod-filter identity)
        share the materialized entry sequence OBJECT: unfiltered items get
        the published view tuple itself (zero copies, identity for free),
        filtered items share one materialized hit list per (filter, key).
        The scorer's batch path keys its per-key weight-map cache by
        object identity, so B requests over a hot shared prefix compute
        each block's weight map once instead of B times. Per-item results
        carry the same entries in the same order as standalone `lookup`
        calls over the same view state (as immutable tuples rather than
        fresh lists on the unfiltered path)."""
        if not requests:
            return []
        refresh = self._refresh
        if refresh <= 1:
            due_items = range(len(requests))
        else:
            # Consume one tick per item (same cadence as N single calls)
            # and refresh exactly the items whose tick lands on the
            # boundary — the same keys-touched-per-tick amortization as
            # the single-call path, not a whole-batch union touch.
            due_items = [
                j for j in range(len(requests))
                if next(self._lookup_tick) % refresh == 0
            ]
        if due_items:
            union: List[Key] = []
            for j in due_items:
                union.extend(requests[j][0])
            for shard, keys in self._group_by_shard(union):
                self._segments[shard].data.get_many(keys)

        view_get = self._view.get
        out: List[Dict[Key, List[PodEntry]]] = []
        shared: dict = {}
        for request_keys, pod_identifier_set in requests:
            if not request_keys:
                raise ValueError("no request keys provided for lookup")
            pods_per_key: Dict[Key, List[PodEntry]] = {}
            if pod_identifier_set:
                tok = id(pod_identifier_set)
                for key in request_keys:
                    entries = view_get(key)
                    if not entries:
                        break  # chain cut (seed semantics), this item only
                    sk = (tok, key)
                    hits = shared.get(sk)
                    if hits is None:
                        hits = shared[sk] = [
                            e for e in entries
                            if pod_matches(e.pod_identifier, pod_identifier_set)
                        ]
                    if hits:
                        pods_per_key[key] = hits
            else:
                for key in request_keys:
                    entries = view_get(key)
                    if not entries:
                        break
                    pods_per_key[key] = entries
            out.append(pods_per_key)
        return out

    def add(
        self,
        engine_keys: Sequence[Key],
        request_keys: Sequence[Key],
        entries: Sequence[PodEntry],
    ) -> None:
        if not engine_keys or not request_keys or not entries:
            raise ValueError("no keys or entries provided for adding to index")
        if len(engine_keys) != len(request_keys):
            raise ValueError(
                f"engine/request key length mismatch: {len(engine_keys)} != {len(request_keys)}"
            )

        # Engine→request mappings, grouped by the ENGINE key's segment.
        pairs_by_shard: Dict[int, List[tuple]] = {}
        for engine_key, request_key in zip(engine_keys, request_keys):
            pairs_by_shard.setdefault(self.shard_of(engine_key), []).append(
                (engine_key, request_key)
            )
        for shard, pairs in pairs_by_shard.items():
            self._segments[shard].engine_to_request.add_many(pairs)

        # Pod-cache inserts, grouped by the REQUEST key's segment. One
        # batched fetch resolves the existing caches; only absent keys pay
        # the double-checked contains_or_add dance (seed semantics).
        view = self._view
        for shard, keys in self._group_by_shard(request_keys):
            seg = self._segments[shard]
            evictions_before = self._evictions
            existing = seg.data.get_many(keys)
            for request_key in keys:
                pod_cache = existing.get(request_key)
                if pod_cache is None:
                    candidate = _ShardPodCache(self._pod_cache_size)
                    contained, _ = seg.data.contains_or_add(request_key, candidate)
                    if contained:
                        pod_cache = seg.data.get(request_key)
                        if pod_cache is None:  # evicted in the window; re-add ours
                            seg.data.add(request_key, candidate)
                            pod_cache = candidate
                    else:
                        pod_cache = candidate
                    existing[request_key] = pod_cache  # duplicate keys in batch
                with pod_cache.mu:
                    for entry in entries:
                        pod_cache.cache.add(entry, None)
                    pod_cache.republish()
                    # Publish under mu: last view writer == last pod-LRU
                    # writer, so the view can't go backwards.
                    view[request_key] = pod_cache.entries
            if self._evictions != evictions_before:
                # An eviction raced this batch somewhere; its callback may
                # have fired before our publishes landed. Re-check so a dead
                # key can't keep a resurrected view entry. Far below
                # capacity (the steady state) this branch never runs.
                for request_key in keys:
                    if seg.data.peek(request_key) is None:
                        view.pop(request_key, None)

    def evict(self, engine_key: Key, entries: Sequence[PodEntry]) -> None:
        if not entries:
            raise ValueError("no entries provided for eviction from index")

        engine_seg = self._segments[self.shard_of(engine_key)]
        request_key = engine_seg.engine_to_request.get(engine_key)
        if request_key is None:
            kvlog.trace(logger, "engine key not in index, nothing to evict: %s", engine_key)
            return

        request_seg = self._segments[self.shard_of(request_key)]
        pod_cache = request_seg.data.get(request_key)
        if pod_cache is None:
            engine_seg.engine_to_request.remove(engine_key)
            return

        view = self._view
        evictions_before = self._evictions
        with pod_cache.mu:
            for entry in entries:
                pod_cache.cache.remove(entry)
            pod_cache.republish()
            view[request_key] = pod_cache.entries
            is_empty = len(pod_cache.cache) == 0
        if self._evictions != evictions_before and request_seg.data.peek(
            request_key
        ) is None:
            view.pop(request_key, None)  # same resurrection guard as add()

        if is_empty:
            # Same re-check as the seed: shrink (not eliminate) the window
            # where a concurrent add repopulates the cache; worst case an
            # empty cache is left behind for LRU to collect.
            current = request_seg.data.get(request_key)
            if current is not None:
                with current.mu:
                    still_empty = len(current.cache) == 0
                if still_empty:
                    request_seg.data.remove(request_key)
                    engine_seg.engine_to_request.remove(engine_key)

    def get_request_key(self, engine_key: Key) -> Optional[Key]:
        return self._segments[self.shard_of(engine_key)].engine_to_request.get(
            engine_key
        )

    def remove_pod(self, pod_identifier: str) -> int:
        """One-pass quarantine purge (Index.remove_pod contract), segment by
        segment — each stripe locks independently, and the read view is
        republished under each pod cache's mutex so concurrent lookups only
        ever see before/after states of a key, never a torn one."""
        target = {pod_identifier}
        removed = 0
        emptied = set()
        view = self._view
        for seg in self._segments:
            for request_key, pod_cache in seg.data.items():
                with pod_cache.mu:
                    victims = [
                        e for e in pod_cache.cache.keys()
                        if pod_matches(e.pod_identifier, target)
                    ]
                    for entry in victims:
                        pod_cache.cache.remove(entry)
                    removed += len(victims)
                    if not victims:
                        continue
                    pod_cache.republish()
                    view[request_key] = pod_cache.entries
                    is_empty = len(pod_cache.cache) == 0
                if is_empty:
                    # The segment LRU's on_evict hook prunes the view entry
                    # under the segment lock.
                    seg.data.remove(request_key)
                    emptied.add(request_key)
        if emptied:
            for seg in self._segments:
                for engine_key, request_key in seg.engine_to_request.items():
                    if request_key in emptied:
                        seg.engine_to_request.remove(engine_key)
        return removed

    def shed(self, fraction: float) -> int:
        """Resource-governor hook: drop the oldest `fraction` of request
        keys in every segment — the LRU tail, exactly what capacity
        eviction would reclaim next, so a shed is indistinguishable from
        running at a smaller index. The segment LRU's on_evict hook
        prunes each dropped key's read-view entry under the segment
        lock; engine mappings pointing at a dropped key are swept after.
        Returns pod entries removed."""
        fraction = min(max(fraction, 0.0), 1.0)
        if fraction <= 0.0:
            return 0
        removed = 0
        emptied = set()
        for seg in self._segments:
            keys = seg.data.keys()
            for request_key in keys[: int(len(keys) * fraction)]:
                pod_cache = seg.data.peek(request_key)
                if pod_cache is None:
                    continue
                with pod_cache.mu:
                    removed += len(pod_cache.cache)
                seg.data.remove(request_key)
                emptied.add(request_key)
        if emptied:
            for seg in self._segments:
                for engine_key, request_key in seg.engine_to_request.items():
                    if request_key in emptied:
                        seg.engine_to_request.remove(engine_key)
        return removed

    def remove_entries(
        self, pod_identifier: str, request_keys, device_tiers=None
    ) -> int:
        """Targeted purge (Index.remove_entries contract). Each touched
        key's read-view entry is REPUBLISHED under its pod cache's mutex
        (same discipline as `add`/`evict`), so concurrent lock-free
        lookups only ever see before/after states of a key — a purged
        phantom stops scoring the moment this returns. Untouched keys are
        accessed via `peek`, keeping their recency order."""
        target = {pod_identifier}
        removed = 0
        emptied = set()
        view = self._view
        for request_key in request_keys:
            seg = self._segments[self.shard_of(request_key)]
            pod_cache = seg.data.peek(request_key)
            if pod_cache is None:
                continue
            with pod_cache.mu:
                victims = [
                    e for e in pod_cache.cache.keys()
                    if pod_matches(e.pod_identifier, target)
                    and (device_tiers is None or e.device_tier in device_tiers)
                ]
                for entry in victims:
                    pod_cache.cache.remove(entry)
                removed += len(victims)
                if not victims:
                    continue
                pod_cache.republish()
                view[request_key] = pod_cache.entries
                is_empty = len(pod_cache.cache) == 0
            if is_empty:
                # The segment LRU's on_evict hook prunes the view entry
                # under the segment lock.
                seg.data.remove(request_key)
                emptied.add(request_key)
        if emptied:
            for seg in self._segments:
                for engine_key, request_key in seg.engine_to_request.items():
                    if request_key in emptied:
                        seg.engine_to_request.remove(engine_key)
        return removed

    def export_view(self) -> IndexView:
        """Snapshot segment by segment, each stripe oldest-first
        (Index.export_view contract). Keys re-stripe identically on
        import (chunk_hash % S is config-independent of insertion
        history), so a same-shape restore reproduces per-segment recency
        exactly; cross-backend restores see segment-grouped order."""
        entries = []
        engine_map = []
        for seg in self._segments:
            for request_key, pod_cache in seg.data.items():
                with pod_cache.mu:
                    pods = tuple(
                        (e.pod_identifier, e.device_tier)
                        for e in pod_cache.cache.keys()
                    )
                entries.append(
                    (request_key.model_name, request_key.chunk_hash, pods)
                )
            for engine_key, request_key in seg.engine_to_request.items():
                engine_map.append((
                    engine_key.model_name, engine_key.chunk_hash,
                    request_key.model_name, request_key.chunk_hash,
                ))
        return IndexView(entries=entries, engine_map=engine_map)

    def import_view(self, view: IndexView) -> int:
        """Rebuild segments + the lock-free read view (Index.import_view).

        Entries publish under each pod cache's mutex exactly like `add`,
        so a replica can import while its read path is already serving —
        lookups see before/after states of a key, never a torn one."""
        imported = 0
        read_view = self._view
        for model_name, chunk_hash, pods in view.entries:
            request_key = Key(model_name, chunk_hash)
            seg = self._segments[self.shard_of(request_key)]
            pod_cache = seg.data.get(request_key)
            if pod_cache is None:
                pod_cache = _ShardPodCache(self._pod_cache_size)
                seg.data.add(request_key, pod_cache)
            with pod_cache.mu:
                for pod, tier in pods:
                    pod_cache.cache.add(PodEntry(pod, tier), None)
                    imported += 1
                pod_cache.republish()
                read_view[request_key] = pod_cache.entries
        for engine_model, engine_hash, req_model, req_hash in view.engine_map:
            engine_key = Key(engine_model, engine_hash)
            self._segments[self.shard_of(engine_key)].engine_to_request.add(
                engine_key, Key(req_model, req_hash)
            )
        return imported

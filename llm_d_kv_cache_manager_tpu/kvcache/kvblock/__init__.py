from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import Key, PodEntry
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    ChunkedTokenDatabase,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
    Index,
    IndexConfig,
    new_index,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
    InMemoryIndex,
    InMemoryIndexConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.sharded import (
    ShardedIndex,
    ShardedIndexConfig,
)

__all__ = [
    "Key",
    "PodEntry",
    "ChunkedTokenDatabase",
    "TokenProcessorConfig",
    "Index",
    "IndexConfig",
    "new_index",
    "InMemoryIndex",
    "InMemoryIndexConfig",
    "ShardedIndex",
    "ShardedIndexConfig",
]

from llm_d_kv_cache_manager_tpu.kvcache.backend import (
    KVCacheBackendConfig,
    default_kv_cache_backend_configs,
)
from llm_d_kv_cache_manager_tpu.kvcache.scorer import (
    KVBlockScorerConfig,
    LongestPrefixScorer,
    new_kv_block_scorer,
)
from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig

__all__ = [
    "KVCacheBackendConfig",
    "default_kv_cache_backend_configs",
    "KVBlockScorerConfig",
    "LongestPrefixScorer",
    "new_kv_block_scorer",
    "Indexer",
    "IndexerConfig",
]

"""Device-tier backends and scoring weights.

Parity target: KVCacheBackendConfig (/root/reference/pkg/kvcache/backend.go:19-31),
retargeted to TPU tiers: a block resident in TPU **HBM** is worth full weight
(served directly by the Pallas paged-attention kernel), a block offloaded to
**host** memory is discounted (it must be DMA'd back over PCIe before use).
The reference's gpu/cpu names are kept as aliases so events from GPU-era
engines still score sensibly. Tier names are fully config-driven.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List


@dataclass
class KVCacheBackendConfig:
    name: str
    weight: float


DEFAULT_TIER_HBM = "hbm"
DEFAULT_TIER_HOST = "host"


def default_kv_cache_backend_configs() -> List[KVCacheBackendConfig]:
    return [
        KVCacheBackendConfig(name=DEFAULT_TIER_HBM, weight=1.0),
        KVCacheBackendConfig(name=DEFAULT_TIER_HOST, weight=0.8),
        # Aliases for engines emitting GPU-era medium names.
        KVCacheBackendConfig(name="gpu", weight=1.0),
        KVCacheBackendConfig(name="cpu", weight=0.8),
    ]


def weight_map(configs: List[KVCacheBackendConfig]) -> Dict[str, float]:
    return {c.name: c.weight for c in configs}

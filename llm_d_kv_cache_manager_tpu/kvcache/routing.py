"""Pluggable routing policy: blend prefix affinity with pod load.

The read path's contract so far has been "score = weighted longest cached
prefix"; the router argmaxes it and ties break least-loaded. That is the
right answer until the fleet saturates: the committed qps ladder
(benchmarking/FLEET_BENCH.json `qps_ladder`) shows the precise arm
degrading to multi-second TTFT p50 at qps_40 with hundreds of
recompute-preemptions, because a pod with the hottest prefix keeps winning
the argmax while its admission queue deepens — prefix score is a *benefit*
signal with no *cost* term.

`RoutingPolicy` adds the cost term at two altitudes:

- **`adjust`** — a post-scoring score-map transformation on the Indexer
  read path (what the scoring API can return):

      effective(pod) = score(pod) / (1 + load_weight * load_index(pod))

  Division (not subtraction) keeps the adjustment scale-free in the
  scorer's units and can demote but never erase or invent a signal — a
  score map has no way to say "route to a pod that isn't in it".
- **`select`** — the full routing decision for callers that know their
  candidate universe (the fleet benches' router; llm-d's EPP blending
  scorer outputs across all endpoints):

      utility(pod) = prefix_frac(pod) - load_weight * load_index(pod)

  over EVERY candidate, cached or not. This is the form in which a
  saturated pod with a perfect prefix genuinely loses to a warm-enough
  idle pod — the idle candidate exists in the decision.

`load_index` is a dimensionless blend of the pod's queue depth,
committed busy time, and decayed preemption rate (fleethealth/load.py),
each scaled by its own normalization knob.

Policies:

- ``prefix_only`` (default) — the identity: `adjust` returns the SAME
  scores dict object, so wiring the policy into the read path is
  bit-identical to not having one (pinned by the byte-identical
  FLEET_BENCH.json rerun and tests/test_routing_policy.py).
- ``load_blend`` — the blend above. Every request whose deterministic
  argmax (max score, lexicographic-min pod) changes under the blend
  counts one `kvcache_routing_policy_overrides_total` — the policy's
  interventions are observable, not folklore.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Optional

from llm_d_kv_cache_manager_tpu.metrics import collector as metrics
from llm_d_kv_cache_manager_tpu.utils import logging as kvlog

logger = kvlog.get_logger("kvcache.routing")

PREFIX_ONLY = "prefix_only"
LOAD_BLEND = "load_blend"
_POLICIES = (PREFIX_ONLY, LOAD_BLEND)


@dataclass
class RoutingPolicyConfig:
    """Env mapping (api/http_service.py): ROUTING_POLICY,
    ROUTING_LOAD_WEIGHT, ROUTING_QUEUE_NORM, ROUTING_BUSY_NORM_S,
    ROUTING_PREEMPTION_NORM."""

    policy: str = PREFIX_ONLY
    # Overall strength of the load discount: 0 disables it numerically
    # (but prefer policy="prefix_only", which skips the walk entirely).
    load_weight: float = 1.0
    # Normalizations: how much of each raw signal equals 1.0 load unit.
    # queue_depth_norm=4 reads "4 queued decodes make a unit of load".
    queue_depth_norm: float = 4.0
    busy_norm_s: float = 1.0
    preemption_norm: float = 8.0

    def __post_init__(self):
        if self.policy not in _POLICIES:
            raise ValueError(
                f"unknown routing policy {self.policy!r}; "
                f"expected one of {_POLICIES}"
            )
        if self.load_weight < 0:
            raise ValueError("load_weight must be >= 0")
        for name in ("queue_depth_norm", "busy_norm_s", "preemption_norm"):
            if getattr(self, name) <= 0:
                raise ValueError(f"{name} must be positive")


def _argmax_pod(scores: Dict[str, float]) -> Optional[str]:
    """The router's deterministic choice: max score, lexicographic-min pod
    (the same tie-break `explain_scores` reports)."""
    if not scores:
        return None
    best = max(scores.values())
    return min(p for p, s in scores.items() if s == best)


class RoutingPolicy:
    """Post-scoring adjustment hook for the Indexer read path."""

    def __init__(
        self,
        config: Optional[RoutingPolicyConfig] = None,
        load_tracker=None,
    ):
        self.config = config or RoutingPolicyConfig()
        # fleethealth.load.PodLoadTracker (duck-typed: load_of(pod, now)).
        # None degrades load_blend to the identity — no signals, no blend.
        self.load_tracker = load_tracker
        self._mu = threading.Lock()
        self.stats = {"adjusted_requests": 0, "overrides": 0}

    @property
    def is_noop(self) -> bool:
        return self.config.policy == PREFIX_ONLY

    def load_index(self, pod_identifier: str, now=None) -> float:
        """Dimensionless per-pod load (0 = idle). Public for explain/status
        surfaces; the blend below is `1 / (1 + load_weight * this)`."""
        if self.load_tracker is None:
            return 0.0
        cfg = self.config
        load = self.load_tracker.load_of(pod_identifier, now=now)
        return (
            load.queue_depth / cfg.queue_depth_norm
            + load.busy_s / cfg.busy_norm_s
            + load.preemption_rate / cfg.preemption_norm
        )

    def adjust(
        self, scores: Dict[str, float], _explain: Optional[dict] = None
    ) -> Dict[str, float]:
        """Blend load into a (post-fleet-health) score map.

        prefix_only, an empty map, no tracker, or zero weight return
        `scores` UNCHANGED — the same dict object, so the pinned
        bit-identity paths never even copy. load_blend returns a new map
        in the scorer's units; entries are demoted, never dropped."""
        if (
            self.is_noop
            or not scores
            or self.load_tracker is None
            or self.config.load_weight == 0.0
        ):
            return scores
        weight = self.config.load_weight
        before = _argmax_pod(scores)
        now = None
        clock = getattr(self.load_tracker, "clock", None)
        if clock is not None:
            now = clock()  # one clock read per request, not per pod
        adjusted: Dict[str, float] = {}
        loads: Dict[str, float] = {}
        for pod, score in scores.items():
            li = self.load_index(pod, now=now)
            loads[pod] = li
            adjusted[pod] = score / (1.0 + weight * li)
        after = _argmax_pod(adjusted)
        with self._mu:
            self.stats["adjusted_requests"] += 1
            if after != before:
                self.stats["overrides"] += 1
        if after != before:
            metrics.count_routing_override()
            kvlog.trace(
                logger,
                "load blend overrode prefix argmax %s -> %s", before, after,
            )
        if _explain is not None:
            _explain["routing_policy"] = {
                "policy": self.config.policy,
                "load_index": {
                    p: round(li, 4) for p, li in sorted(loads.items())
                },
                "override": after != before,
                "prefix_choice": before,
                "blended_choice": after,
            }
        return adjusted

    def score_divisors(self, pod_identifiers):
        """Per-pod score divisors for the native scoring core.

        `adjust` computes ``score / (1 + load_weight * load_index)``; this
        returns the denominators aligned with `pod_identifiers`, or None
        when the blend is inert (prefix_only / no tracker / zero weight) —
        the unchanged-scores identity path. One clock read for the whole
        batch, like `adjust`'s one read per request. ``None`` input
        entries (the interner's id-0 sentinel) get the neutral 1.0.
        """
        if (
            self.is_noop
            or self.load_tracker is None
            or self.config.load_weight == 0.0
        ):
            return None
        weight = self.config.load_weight
        now = None
        clock = getattr(self.load_tracker, "clock", None)
        if clock is not None:
            now = clock()
        return [
            1.0 if pod is None
            else 1.0 + weight * self.load_index(pod, now=now)
            for pod in pod_identifiers
        ]

    def note_adjusted(self, adjusted: int, overrides: int) -> None:
        """Fold a native batch's blend accounting into `stats` — the same
        counters `adjust` keeps per request, minus the per-override trace
        log (the native path only knows the override happened, not the
        pod names)."""
        if adjusted <= 0:
            return
        with self._mu:
            self.stats["adjusted_requests"] += adjusted
            self.stats["overrides"] += overrides
        for _ in range(overrides):
            metrics.count_routing_override()

    def select(
        self,
        scores: Dict[str, float],
        candidate_pods,
        now=None,
        _explain: Optional[dict] = None,
    ) -> Optional[str]:
        """Full routing decision over a KNOWN candidate set.

        `adjust` can only demote entries inside the score map — a pod with
        no cache signal is not in the map, so a map transformation can
        never express "the saturated perfect-prefix pod loses to a
        warm-enough idle pod with no cache at all". The router, which
        knows its candidate universe, gets the full blend instead:

            utility(pod) = prefix_frac(pod) - load_weight * load_index(pod)

        where prefix_frac normalizes the pod's prefix score against the
        request's best (1.0 = the longest cached prefix anyone has, 0 =
        no cache) — so `load_weight` reads as "how many units of
        normalized load one full prefix hit is worth". Deterministic
        tie-break: max utility, lexicographic-min pod. Returns None under
        `prefix_only` (or with no tracker/zero weight): the caller's pure
        prefix argmax stays authoritative — and bit-identical.

        Every selection whose winner differs from the pure prefix argmax
        counts one `kvcache_routing_policy_overrides_total`.
        """
        candidates = list(dict.fromkeys(candidate_pods))
        if (
            self.is_noop
            or not candidates
            or self.load_tracker is None
            or self.config.load_weight == 0.0
        ):
            return None
        if now is None:
            clock = getattr(self.load_tracker, "clock", None)
            if clock is not None:
                now = clock()
        max_score = max(scores.values()) if scores else 0.0
        weight = self.config.load_weight
        utilities: Dict[str, float] = {}
        loads: Dict[str, float] = {}
        for pod in candidates:
            li = self.load_index(pod, now=now)
            loads[pod] = li
            frac = (scores.get(pod, 0.0) / max_score) if max_score else 0.0
            utilities[pod] = frac - weight * li
        best = max(utilities.values())
        chosen = min(p for p, u in utilities.items() if u == best)
        prefix_choice = _argmax_pod(
            {p: s for p, s in scores.items() if p in utilities}
        )
        overrode = prefix_choice is not None and chosen != prefix_choice
        with self._mu:
            self.stats["adjusted_requests"] += 1
            if overrode:
                self.stats["overrides"] += 1
        if overrode:
            metrics.count_routing_override()
            kvlog.trace(
                logger,
                "load blend overrode prefix argmax %s -> %s",
                prefix_choice, chosen,
            )
        if _explain is not None:
            _explain["routing_policy"] = {
                "policy": self.config.policy,
                "load_index": {
                    p: round(li, 4) for p, li in sorted(loads.items())
                },
                "utility": {
                    p: round(u, 4) for p, u in sorted(utilities.items())
                },
                "override": overrode,
                "prefix_choice": prefix_choice,
                "blended_choice": chosen,
            }
        return chosen

    def status(self) -> dict:
        cfg = self.config
        with self._mu:
            stats = dict(self.stats)
        return {
            "policy": cfg.policy,
            "load_weight": cfg.load_weight,
            "queue_depth_norm": cfg.queue_depth_norm,
            "busy_norm_s": cfg.busy_norm_s,
            "preemption_norm": cfg.preemption_norm,
            "stats": stats,
            "loads": (
                self.load_tracker.snapshot()
                if self.load_tracker is not None else None
            ),
        }

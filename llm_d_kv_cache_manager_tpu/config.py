"""JSON config loading for the whole module tree.

Parity target: the reference's JSON-serializable nested config structs
(/root/reference/docs/configuration.md, indexer.go:36-60): every module has
a dataclass config with working defaults; this module round-trips the whole
IndexerConfig tree to/from JSON so deployments can ship one config file.

Keys are the dataclass field names; unknown keys error loudly (config typos
must not silently fall back to defaults — the hash_seed/block_size
invariants make silent fallback dangerous).
"""

from __future__ import annotations

import dataclasses
import json
import typing
from typing import Any, Dict, Type, TypeVar

T = TypeVar("T")


def _from_dict(cls: Type[T], data: Dict[str, Any], path: str = "") -> T:
    if not dataclasses.is_dataclass(cls):
        return data  # leaf passthrough (e.g. plain values)
    fields = {f.name for f in dataclasses.fields(cls)}
    # get_type_hints resolves string/Optional annotations against the class's
    # module; forward references to classes the module deliberately does not
    # import (index.py avoids circular imports) resolve via localns.
    hints = typing.get_type_hints(cls, localns=_forward_refs())
    kwargs = {}
    for key, value in data.items():
        if key not in fields:
            raise ValueError(f"unknown config key {path + key!r} for {cls.__name__}")
        resolved = _unwrap(hints.get(key))
        if dataclasses.is_dataclass(resolved) and isinstance(value, dict):
            kwargs[key] = _from_dict(resolved, value, path=f"{path}{key}.")
        elif isinstance(value, list):
            item_type = _list_item_type(hints.get(key))
            if dataclasses.is_dataclass(item_type):
                kwargs[key] = [
                    _from_dict(item_type, v, path=f"{path}{key}[].") for v in value
                ]
            else:
                kwargs[key] = value
        else:
            kwargs[key] = value
    return cls(**kwargs)


def _unwrap(annotation):
    """Unwrap Optional[X] / Union[X, None] to X (unions only, not List etc.)."""
    if annotation is None:
        return None
    if typing.get_origin(annotation) is typing.Union:
        args = [a for a in typing.get_args(annotation) if a is not type(None)]
        if args:
            return args[0]
    return annotation


def _list_item_type(annotation):
    resolved = _unwrap(annotation)
    if typing.get_origin(resolved) in (list, tuple):
        args = typing.get_args(resolved)
        return args[0] if args else None
    return None


def _forward_refs() -> Dict[str, type]:
    """Classes referenced by string annotations across the config tree."""
    from llm_d_kv_cache_manager_tpu.kvcache.kvblock.cost_aware import (
        CostAwareIndexConfig,
    )
    from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
        InMemoryIndexConfig,
    )
    from llm_d_kv_cache_manager_tpu.kvcache.kvblock.redis_index import (
        RedisIndexConfig,
    )

    return {
        "InMemoryIndexConfig": InMemoryIndexConfig,
        "CostAwareIndexConfig": CostAwareIndexConfig,
        "RedisIndexConfig": RedisIndexConfig,
    }


def indexer_config_from_json(payload: str):
    """Build an IndexerConfig from a JSON document."""
    from llm_d_kv_cache_manager_tpu.kvcache.indexer import IndexerConfig

    return _from_dict(IndexerConfig, json.loads(payload))


def config_to_json(config) -> str:
    """Serialize any config dataclass tree to JSON."""
    def encode(obj):
        if dataclasses.is_dataclass(obj):
            return {
                f.name: encode(getattr(obj, f.name))
                for f in dataclasses.fields(obj)
            }
        if isinstance(obj, (list, tuple)):
            return [encode(x) for x in obj]
        return obj

    return json.dumps(encode(config), indent=2, default=str)

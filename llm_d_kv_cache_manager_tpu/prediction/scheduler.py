"""Budget-bounded anticipatory prefetch: pre-land the next turn.

The session table (prediction/sessions.py) says *when* a session's next
turn is expected and *what* chain it will lead with; this module decides
*where* that chain should already be, and pushes it there during the idle
think window — through the planes that already exist, with serving always
winning:

- **The target is the router's answer, not a guess.** The chain is scored
  through the REAL read-path stages (`Indexer.score_hashes`: same index
  lookup, same scorer arithmetic, same fleet-health filtering, same
  routing-policy adjustment), and the pod is picked by the caller's own
  tie-break (`select_fn` — the bench passes the router's exact rule). A
  prediction can be early or wasted; it can never disagree with where the
  router would send the request.
- **Jobs ride the bounded prefetch plane.** Submissions go through a
  `RoutePrefetcher` under `source="prediction"`, so they inherit its
  non-blocking bounded queue and are dropped (counted, per source) rather
  than ever queueing behind serving. Downstream, `EnginePod.warm_chain`
  admits through the data plane only and aborts on `OutOfPagesError` —
  page pressure from live traffic silently wins.
- **Budgets are structural.** Per-tick job cap, per-session cooldown, and
  the idle-window gate (no prefetch while the response is still
  streaming; none for a turn already overdue past the expiry horizon).

The tick is pull-based and thread-free, like the placement replicator:
callers invoke `tick()` from whatever cadence they own (the fleet sim
calls it per served request under the simulated clock; a service wires a
timer). Every decision is visible in `stats` and Prometheus counters.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, List, Optional

from llm_d_kv_cache_manager_tpu import obs
from llm_d_kv_cache_manager_tpu.metrics import collector as metrics
from llm_d_kv_cache_manager_tpu.prediction.sessions import (
    SessionRecord,
    SessionTable,
)
from llm_d_kv_cache_manager_tpu.utils import logging as kvlog

logger = kvlog.get_logger("prediction.scheduler")

# submit_fn(pod_identifier, block_hashes) -> bool: enqueue one prefetch
# job (False = bounded queue full / plane closed, counted as a drop).
# Typically `lambda pod, hashes: prefetcher.submit(pod, hashes,
# source="prediction")`.
SubmitFn = Callable[[str, List[int]], bool]
# score_fn(model_name, block_hashes) -> PodScores: the real routing
# decision over an already-derived chain (Indexer.score_hashes; tenant
# scoping needs no extra argument — the adapter id is already mixed into
# every chunk hash).
ScoreFn = Callable[..., object]
# select_fn(scores: dict) -> Optional[pod_identifier]: the router's
# tie-break over the score map. None = no target (skip this session).
SelectFn = Callable[[dict], Optional[str]]


@dataclass
class SchedulerConfig:
    # Bound on prefetch jobs one tick may submit (serving-first: a burst
    # of simultaneously-due sessions trickles out over ticks).
    max_jobs_per_tick: int = 4
    # Per-session cooldown between prefetch attempts — a session whose
    # prefetch was dropped (or partially landed) is not retried in a hot
    # loop.
    session_cooldown_s: float = 5.0
    # Idle-window entry: wait this fraction of the predicted gap after the
    # last arrival before prefetching (the pod is busy streaming the
    # response early in the gap; mid-think competes with nothing).
    start_frac: float = 0.25


def best_score_select(scores: dict) -> Optional[str]:
    """Default deterministic tie-break: best score, lexicographic-min pod
    (the same rule `Indexer.explain_scores` reports as `chosen`). Callers
    with load state pass their own rule instead."""
    if not scores:
        return None
    best = max(scores.values())
    return min(p for p, s in scores.items() if s == best)


class PrefetchScheduler:
    """Policy loop: find sessions in their idle window, resolve the
    router's target pod, pre-land the continuation prefix."""

    def __init__(
        self,
        table: SessionTable,
        score_fn: ScoreFn,
        submit_fn: SubmitFn,
        config: Optional[SchedulerConfig] = None,
        select_fn: Optional[SelectFn] = None,
        clock=time.monotonic,
    ):
        self.table = table
        self.score_fn = score_fn
        self.submit_fn = submit_fn
        self.config = config or SchedulerConfig()
        self.select_fn = select_fn or best_score_select
        self.clock = clock
        self.stats = {
            "ticks": 0,
            "jobs_submitted": 0,
            "blocks_submitted": 0,
            "drops": 0,
            "skipped_no_target": 0,
            "expired": 0,
        }

    def tick(self, now: Optional[float] = None) -> int:
        """One policy pass; returns the number of jobs submitted."""
        if now is None:
            now = self.clock()
        cfg = self.config
        self.stats["ticks"] += 1
        self.stats["expired"] += self.table.expire_pending(now)
        due = self.table.due_sessions(
            now,
            start_frac=cfg.start_frac,
            cooldown_s=cfg.session_cooldown_s,
            limit=cfg.max_jobs_per_tick,
        )
        if not due:
            return 0
        # Only working ticks trace (prediction plane stage attribution):
        # an idle tick is the overwhelmingly common case and must not
        # churn the flight-recorder ring.
        submitted = 0
        with obs.request("prediction.tick", {"due": len(due)}):
            for rec, expected_at in due:
                if submitted >= cfg.max_jobs_per_tick:
                    break
                if self._prefetch(rec, now):
                    submitted += 1
                    kvlog.trace(
                        logger,
                        "anticipatory prefetch for session %x "
                        "(expected in %.2fs)",
                        rec.tail, expected_at - now,
                    )
        return submitted

    def _prefetch(self, rec: SessionRecord, now: float) -> bool:
        if not rec.chain_hashes:
            return False
        with obs.stage("prediction.score_hashes", nested=True):
            result = self.score_fn(rec.model_name, rec.chain_hashes)
        pod = self.select_fn(result.scores)
        if pod is None:
            self.stats["skipped_no_target"] += 1
            return False
        # The WHOLE retained chain is submitted, not the index-derived
        # missing tail: the index cannot distinguish device-resident
        # blocks (nothing to do) from host-staged ones (the evicted
        # prefix this subsystem exists to re-land) — both count toward a
        # pod's matched prefix. The pod-side admission is residency-aware
        # and idempotent (prefetch_hashes filters resident blocks;
        # warm_chain materializes only what some tier can supply), so
        # over-submission costs a queue slot, never a wasted transfer.
        with obs.stage("prediction.submit"):
            submitted = self.submit_fn(pod, list(rec.chain_hashes))
        if submitted:
            self.table.note_prefetch(rec, pod, now)
            self.stats["jobs_submitted"] += 1
            self.stats["blocks_submitted"] += len(rec.chain_hashes)
            metrics.count_prediction_prefetch(len(rec.chain_hashes))
            return True
        self.stats["drops"] += 1
        return False

    def register_knobs(self, registry) -> None:
        """Publish the per-tick prefetch budget to the autopilot
        (autopilot/knobs.py). tick() re-reads the config each pass. The
        floor is 1, not 0: `due_sessions(limit=0)` means UNLIMITED, so a
        zeroed knob would widen the budget it exists to shrink."""
        from llm_d_kv_cache_manager_tpu.autopilot.knobs import (
            KNOB_PREDICTION_JOBS,
            KnobSpec,
        )

        cfg = self.config
        registry.register(
            KnobSpec(
                name=KNOB_PREDICTION_JOBS,
                floor=1.0,
                ceiling=float(max(cfg.max_jobs_per_tick * 2, 2)),
                max_step=1.0,
                integer=True,
                description="anticipatory prefetch jobs submitted per tick",
            ),
            get=lambda: cfg.max_jobs_per_tick,
            set_=lambda v: setattr(cfg, "max_jobs_per_tick", int(v)),
        )

    def status(self) -> dict:
        return {
            "config": {
                "max_jobs_per_tick": self.config.max_jobs_per_tick,
                "session_cooldown_s": self.config.session_cooldown_s,
                "start_frac": self.config.start_frac,
            },
            "stats": dict(self.stats),
            "table": self.table.stats(),
        }

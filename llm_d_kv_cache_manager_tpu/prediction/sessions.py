"""Session table: learn per-session next-turn ETAs from the read path.

Every plane before this one reacts to an arrival; this module is the
memory that lets the fleet act *before* one. The read path already derives
each routed prompt's block-hash chain (`Indexer.get_pod_scores_ex`), and a
multi-turn session's turns are chained by construction: turn N's prompt
extends turn N-1's grown prompt, so turn N's chain carries turn N-1's
entire chain as a leading prefix. That containment is the session
identity — no session id, cookie, or router affinity is needed:

- a session is keyed by the **tail hash** of its latest observed chain
  (the last block's hash). Tenant/LoRA extra keys are already mixed into
  every chunk hash (hashing.py), so two tenants' identical token streams
  have disjoint tails — per-tenant isolation rides the same mechanism the
  index itself uses, and sessions sharing a system-prefix group still
  diverge at their first user message.
- a new observation whose chain *contains* a tracked tail is that
  session's next turn: the gap since the previous arrival is a think-time
  sample, and the record re-keys to the new tail.

The think-time model is deliberately small: a per-session EWMA over
observed inter-turn gaps, blended with a **fleet-level quantile prior**
(a bounded reservoir over every session's gaps, seeded from the
workloads/ think-time shape) by observation count — a session's first
continuation is predicted almost entirely by the fleet, its fifth almost
entirely by itself. Everything runs under an injected clock, one mutex,
hard space bounds (LRU past `max_sessions`), and observation is the only
write path — scores and routing are bit-identical with a table attached
(the PREDICTION=0 contract, pinned in tests/test_prediction.py).

Misprediction accounting is first-class: every prefetch the scheduler
lands is noted on the record, and blocks that were pre-landed for a turn
that never arrived (prediction expired, session evicted) — or landed on a
pod the router then did not pick — are counted, in blocks and bytes. The
anticipate bench commits that number as its honest cost column.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from llm_d_kv_cache_manager_tpu.metrics import collector as metrics


@dataclass
class PredictionConfig:
    """Knobs of the session predictor; all bounds are hard."""

    # Session-table bound: past it, the least-recently-observed session is
    # evicted (an outstanding prefetch on the victim counts mispredicted).
    max_sessions: int = 1024
    # EWMA weight of the newest inter-turn gap sample (0..1]. Higher adapts
    # faster; lower smooths tool-latency jitter.
    eta_alpha: float = 0.4
    # How many pseudo-observations the fleet prior is worth when blending
    # with the per-session EWMA: eta = (n*ewma + w*prior) / (n + w).
    prior_weight: float = 2.0
    # Bounded reservoir of recent fleet-wide gap samples (any session),
    # and the quantile of it used as the prior.
    fleet_window: int = 256
    fleet_quantile: float = 0.5
    # Cold-start prior before the fleet has observed a single continuation
    # (the workloads/ think-time shape: mean think time plus read time for
    # a median-length response — see `fleet_prior_from_tables`).
    default_eta_s: float = 8.0
    # Sanity clamps on gap samples: sub-min gaps are request fan-out (two
    # arrivals of one logical turn), beyond-max gaps are abandoned
    # sessions coming back — neither should steer the EWMA.
    min_gap_s: float = 0.05
    max_gap_s: float = 600.0
    # Retained continuation prefix per session (blocks, and the matching
    # token slice a warm_chain admission needs).
    max_chain_blocks: int = 256
    # Chain-tail safety margin, in blocks. The tokenization pool's
    # prefix-store shortcut is tail-unstable: the FIRST (cold) tokenization
    # of a prompt can yield a few more trailing tokens than every later
    # (store-hit) call, so a route-time observed chain may end in blocks
    # no engine ever commits. Predictions cover only the stable prefix —
    # the dropped tail is at most this many blocks of the next turn's
    # prefill, while a phantom tail block would head-block the whole
    # chain restore (warm_chain materializes a leading prefix). The
    # store's shortcut re-tokenizes at most one 256-byte text chunk of
    # tail (~5 blocks at block_size 16); 8 is that bound with margin.
    tail_trim_blocks: int = 8
    # A pending prefetch expires (mispredicted) this many ETAs past the
    # predicted arrival.
    expiry_factor: float = 3.0
    # Bytes per KV block for the mispredicted-bytes accounting (0 = count
    # blocks only; the bench passes the model class's real block bytes).
    block_bytes: int = 0


def fleet_prior_from_tables(
    think_time_mean_s: float,
    read_s_per_unit: float,
    quantile: float = 0.5,
) -> float:
    """Static ETA prior from the committed workload tables: mean think
    time plus the read-time term for a `quantile` response length — the
    same shape `workloads.arrivals.think_time_s` draws from, collapsed to
    one number for cold-start prediction."""
    from llm_d_kv_cache_manager_tpu.workloads import tables

    qs = tables.OUTPUT_LEN_QUANTILES
    q = min(max(quantile, 0.0), 1.0)
    # Piecewise-linear inverse CDF over the committed quantile table.
    pos = q * (len(qs) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(qs) - 1)
    out_len = qs[lo][1] + (qs[hi][1] - qs[lo][1]) * (pos - lo)
    return think_time_mean_s + read_s_per_unit * out_len


@dataclass
class PendingPrefetch:
    """One outstanding anticipatory prefetch, noted on the session."""

    pod: str
    blocks: int
    submitted_at: float
    expected_at: float
    expires_at: float


@dataclass
class SessionRecord:
    """One tracked session: its latest chain and think-time estimate."""

    tail: int                      # last block hash of the latest chain
    lora_id: Optional[int]
    model_name: str
    chain_hashes: List[int] = field(default_factory=list)
    tokens: List[int] = field(default_factory=list)
    last_arrival_s: float = 0.0
    gap_ewma_s: Optional[float] = None
    turns_observed: int = 1
    gap_samples: int = 0
    # Lifecycle of the anticipatory prefetch for the NEXT turn.
    pending: Optional[PendingPrefetch] = None
    last_prefetch_at: Optional[float] = None
    # The prefetch consumed by the CURRENT turn (set when a continuation
    # resolves a pending prefetch; the bench's audit compares its pod with
    # the router's actual pick).
    consumed: Optional[PendingPrefetch] = None

    def observe_gap(self, gap_s: float, alpha: float) -> None:
        self.gap_samples += 1
        if self.gap_ewma_s is None:
            self.gap_ewma_s = gap_s
        else:
            self.gap_ewma_s += alpha * (gap_s - self.gap_ewma_s)


class SessionTable:
    """Bounded read-path observer: session identity, ETA, prefix memory.

    Attach as `Indexer(prediction=table)` — `observe_route` is called with
    the same arguments the placement popularity ingest gets, is pure
    observation (never read by the scoring stages), and costs one
    attribute check when disabled (`None`).
    """

    def __init__(
        self,
        config: Optional[PredictionConfig] = None,
        clock=time.monotonic,
    ):
        self.config = config or PredictionConfig()
        if self.config.max_sessions <= 0:
            raise ValueError("max_sessions must be positive")
        if not 0.0 < self.config.eta_alpha <= 1.0:
            raise ValueError("eta_alpha must be in (0, 1]")
        self.clock = clock
        self._mu = threading.Lock()
        # tail hash -> record, LRU by last observation. Tenant extras are
        # already mixed into the tail hash, so one flat map is isolated.
        self._by_tail: "OrderedDict[int, SessionRecord]" = OrderedDict()
        self._fleet_gaps: "deque[float]" = deque(
            maxlen=max(self.config.fleet_window, 1)
        )
        self.stats_counters = {
            "observations": 0,
            "continuations": 0,
            "new_sessions": 0,
            "evictions": 0,
            "prefetches_noted": 0,
            "prefetches_resolved": 0,
            "prefetches_expired": 0,
            "mispredicted_blocks": 0,
            "mispredicted_bytes": 0,
            "clamped_gaps": 0,
            "shed_sessions": 0,
        }

    # -- ingest (the Indexer observation seam) -----------------------------

    def observe_route(
        self,
        block_hashes: Sequence[int],
        tokens: Optional[Sequence[int]] = None,
        lora_id: Optional[int] = None,
        model_name: str = "",
        block_size: int = 0,
        now: Optional[float] = None,
    ) -> None:
        """One routed request: continuation detection + ETA update.

        Same signature as the placement tracker's route ingest, so the
        Indexer seam feeds both with one call shape."""
        if not block_hashes:
            return
        if now is None:
            now = self.clock()
        cfg = self.config
        retained = self._retained_slice(block_hashes)
        if not retained:
            return
        with self._mu:
            self.stats_counters["observations"] += 1
            rec = self._find_continuation(block_hashes)
            if rec is not None:
                self._continue_session(
                    rec, retained, tokens, block_size, now
                )
            else:
                rec = SessionRecord(
                    tail=retained[-1],
                    lora_id=lora_id,
                    model_name=model_name,
                    last_arrival_s=now,
                )
                self._retain_chain(rec, retained, tokens, block_size)
                self._by_tail[rec.tail] = rec
                self.stats_counters["new_sessions"] += 1
            self._by_tail.move_to_end(rec.tail)
            while len(self._by_tail) > cfg.max_sessions:
                _, victim = self._by_tail.popitem(last=False)
                self.stats_counters["evictions"] += 1
                if victim.pending is not None:
                    self._count_mispredicted(victim.pending.blocks)

    def _find_continuation(
        self, block_hashes: Sequence[int]
    ) -> Optional[SessionRecord]:
        """The tracked session (if any) whose latest chain is a leading
        prefix of this one. Scanned back-to-front: the previous turn's
        tail sits near the end of the new chain (only the new user
        message extends it), so the match is found in a handful of dict
        probes."""
        by_tail = self._by_tail
        for h in reversed(block_hashes):
            rec = by_tail.get(h)
            if rec is not None:
                return rec
        return None

    def _retained_slice(self, block_hashes: Sequence[int]) -> List[int]:
        """The chain slice a record keeps: bounded AND tail-trimmed (see
        `tail_trim_blocks` — the trailing blocks of a cold tokenization
        are not trustworthy prediction targets)."""
        cfg = self.config
        n = len(block_hashes) - max(cfg.tail_trim_blocks, 0)
        return list(block_hashes[: min(max(n, 1), cfg.max_chain_blocks)])

    def _continue_session(
        self,
        rec: SessionRecord,
        block_hashes: Sequence[int],
        tokens: Optional[Sequence[int]],
        block_size: int,
        now: float,
    ) -> None:
        cfg = self.config
        self.stats_counters["continuations"] += 1
        gap = now - rec.last_arrival_s
        if cfg.min_gap_s <= gap <= cfg.max_gap_s:
            rec.observe_gap(gap, cfg.eta_alpha)
            self._fleet_gaps.append(gap)
        else:
            self.stats_counters["clamped_gaps"] += 1
        rec.turns_observed += 1
        rec.last_arrival_s = now
        # The pending prefetch (if any) is consumed by this arrival — the
        # predicted turn happened. Whether it landed on the right pod is
        # the caller's audit (`consumed` carries the evidence).
        rec.consumed = rec.pending
        if rec.pending is not None:
            self.stats_counters["prefetches_resolved"] += 1
            rec.pending = None
        # Re-key to the new tail.
        old_tail = rec.tail
        new_tail = block_hashes[-1]
        if new_tail != old_tail:
            self._by_tail.pop(old_tail, None)
            rec.tail = new_tail
            self._by_tail[new_tail] = rec
        self._retain_chain(rec, block_hashes, tokens, block_size)

    def _retain_chain(
        self,
        rec: SessionRecord,
        block_hashes: Sequence[int],
        tokens: Optional[Sequence[int]],
        block_size: int,
    ) -> None:
        rec.chain_hashes = list(block_hashes)
        if tokens is not None and block_size > 0:
            # Exactly the retained chain's token span, so a warm_chain
            # re-derivation from these tokens yields exactly chain_hashes.
            rec.tokens = list(tokens[: len(rec.chain_hashes) * block_size])

    def _count_mispredicted(self, blocks: int) -> None:
        self.stats_counters["mispredicted_blocks"] += blocks
        self.stats_counters["mispredicted_bytes"] += (
            blocks * self.config.block_bytes
        )
        metrics.count_prediction_mispredicted(blocks)

    # -- ETA model ---------------------------------------------------------

    def fleet_eta_s(self) -> float:
        """Fleet-level prior: the configured quantile of the recent-gap
        reservoir, or the cold-start default before any continuation."""
        with self._mu:
            return self._fleet_eta_locked()

    def _fleet_eta_locked(self) -> float:
        if not self._fleet_gaps:
            return self.config.default_eta_s
        ordered = sorted(self._fleet_gaps)
        q = min(max(self.config.fleet_quantile, 0.0), 1.0)
        return ordered[min(int(q * len(ordered)), len(ordered) - 1)]

    def _eta_locked(self, rec: SessionRecord) -> float:
        prior = self._fleet_eta_locked()
        if rec.gap_ewma_s is None:
            return prior
        n = rec.gap_samples
        w = self.config.prior_weight
        return (n * rec.gap_ewma_s + w * prior) / (n + w)

    def eta_s(self, rec: SessionRecord) -> float:
        """Blended next-turn ETA (seconds after the last arrival)."""
        with self._mu:
            return self._eta_locked(rec)

    # -- scheduler surface -------------------------------------------------

    def due_sessions(
        self,
        now: float,
        start_frac: float = 0.25,
        cooldown_s: float = 5.0,
        limit: int = 0,
    ) -> List[Tuple[SessionRecord, float]]:
        """Sessions inside their predicted idle window with no outstanding
        prefetch and a cooled-down last attempt: [(record, expected_at)],
        soonest expected arrival first. The window opens `start_frac` of
        the ETA after the last arrival (the pod is still streaming the
        response right after the request; mid-think is when prefetch
        competes with nothing) and closes at the expiry horizon."""
        out: List[Tuple[SessionRecord, float]] = []
        with self._mu:
            for rec in self._by_tail.values():
                if rec.pending is not None:
                    continue
                if (
                    rec.last_prefetch_at is not None
                    and now - rec.last_prefetch_at < cooldown_s
                ):
                    continue
                eta = self._eta_locked(rec)
                expected = rec.last_arrival_s + eta
                opens = rec.last_arrival_s + start_frac * eta
                closes = expected + self.config.expiry_factor * eta
                if opens <= now <= closes:
                    out.append((rec, expected))
        out.sort(key=lambda item: (item[1], item[0].tail))
        if limit > 0:
            out = out[:limit]
        return out

    def note_prefetch(self, rec: SessionRecord, pod: str, now: float) -> None:
        """Record a submitted anticipatory prefetch on the session.
        `blocks` starts at 0 — misprediction cost counts bytes actually
        MOVED, and only the executor knows how many landed
        (`note_landed`); a prefetch that found everything device-resident
        costs nothing and must expire costing nothing."""
        with self._mu:
            eta = self._eta_locked(rec)
            expected = rec.last_arrival_s + eta
            rec.pending = PendingPrefetch(
                pod=pod,
                blocks=0,
                submitted_at=now,
                expected_at=expected,
                expires_at=expected + self.config.expiry_factor * eta,
            )
            rec.last_prefetch_at = now
            self.stats_counters["prefetches_noted"] += 1

    def note_landed(self, tail: int, blocks: int) -> None:
        """Executor feedback: `blocks` were actually transferred for the
        pending prefetch keyed by `tail` (the submitted chain's last
        hash). Lost-race lookups (the session re-keyed because its turn
        already arrived) are fine to drop — a consumed prefetch's cost is
        audited through `consumed`, not `pending`."""
        if not blocks:
            return
        with self._mu:
            rec = self._by_tail.get(tail)
            if rec is not None and rec.pending is not None:
                rec.pending.blocks += blocks

    def expire_pending(self, now: float) -> int:
        """Sweep predictions whose turn never arrived: their blocks are
        mispredicted cost. Returns how many predictions expired."""
        expired = 0
        with self._mu:
            for rec in self._by_tail.values():
                p = rec.pending
                if p is not None and now > p.expires_at:
                    self._count_mispredicted(p.blocks)
                    self.stats_counters["prefetches_expired"] += 1
                    rec.pending = None
                    expired += 1
        return expired

    def shed(self, fraction: float) -> int:
        """Resource-governor hook: evict the `fraction` least-recently-
        observed sessions, SKIPPING any with an outstanding prefetch —
        an in-flight prediction's misprediction accounting rides the
        record, so dropping it would both lose cost evidence and orphan
        the executor's `note_landed` feedback. Sessions are re-learned
        from their next turn (as a fresh session, losing only the ETA
        history). Returns sessions evicted."""
        fraction = min(max(fraction, 0.0), 1.0)
        with self._mu:
            target = int(len(self._by_tail) * fraction)
            if target <= 0:
                return 0
            victims = [
                tail for tail, rec in self._by_tail.items()
                if rec.pending is None
            ][:target]
            for tail in victims:
                del self._by_tail[tail]
            self.stats_counters["shed_sessions"] += len(victims)
            return len(victims)

    # -- queries -----------------------------------------------------------

    def record_by_tail(self, tail: int) -> Optional[SessionRecord]:
        with self._mu:
            return self._by_tail.get(tail)

    def count_wrong_pod(self, blocks: int) -> None:
        """Caller-observed misprediction: the turn arrived but the router
        picked a different pod than the prefetch landed on (the bench's
        audit seam — the table cannot see routing decisions)."""
        with self._mu:
            self._count_mispredicted(blocks)

    def sessions(self) -> int:
        with self._mu:
            return len(self._by_tail)

    def snapshot(self, now: Optional[float] = None, limit: int = 8) -> list:
        """Introspection (the /prediction/status surface): the `limit`
        soonest-expected sessions with their ETA evidence."""
        if now is None:
            now = self.clock()
        with self._mu:
            rows = []
            for rec in self._by_tail.values():
                eta = self._eta_locked(rec)
                rows.append({
                    "tail": f"{rec.tail:016x}",
                    "turns_observed": rec.turns_observed,
                    "eta_s": round(eta, 3),
                    "expected_in_s": round(
                        rec.last_arrival_s + eta - now, 3
                    ),
                    "chain_blocks": len(rec.chain_hashes),
                    "gap_ewma_s": (
                        round(rec.gap_ewma_s, 3)
                        if rec.gap_ewma_s is not None else None
                    ),
                    "pending_prefetch": (
                        {"pod": rec.pending.pod, "blocks": rec.pending.blocks}
                        if rec.pending is not None else None
                    ),
                })
            rows.sort(key=lambda r: r["expected_in_s"])
            return rows[:limit]

    def stats(self) -> Dict[str, float]:
        with self._mu:
            return {
                "tracked_sessions": len(self._by_tail),
                "max_sessions": self.config.max_sessions,
                "fleet_eta_s": round(self._fleet_eta_locked(), 4),
                "fleet_gap_samples": len(self._fleet_gaps),
                **self.stats_counters,
            }

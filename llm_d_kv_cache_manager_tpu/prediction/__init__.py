"""Anticipatory prefetch: pre-land a session's next turn before it arrives.

- sessions.py: bounded session table learning per-session next-turn ETAs
  (EWMA blended with a fleet-level quantile prior) and continuation
  prefixes from the read path's chain observations.
- scheduler.py: budget-bounded prefetch loop resolving the target pod via
  the REAL routing decision and riding the existing prefetch/warm_chain
  admission seams — serving always wins.
"""

from llm_d_kv_cache_manager_tpu.prediction.scheduler import (
    PrefetchScheduler,
    SchedulerConfig,
    best_score_select,
)
from llm_d_kv_cache_manager_tpu.prediction.sessions import (
    PendingPrefetch,
    PredictionConfig,
    SessionRecord,
    SessionTable,
    fleet_prior_from_tables,
)

__all__ = [
    "PendingPrefetch",
    "PredictionConfig",
    "PrefetchScheduler",
    "SchedulerConfig",
    "SessionRecord",
    "SessionTable",
    "best_score_select",
    "fleet_prior_from_tables",
]

"""cluster: the replicated indexer control plane.

N indexer replicas running as one logical index — the first step from "a
library with benches" to a control plane that survives its own restarts
(ROADMAP "Scale out the indexer itself"). Three pillars:

- **Stream partitioning** (`partition.py`): each (pod, dp_rank) event topic
  is owned by exactly one replica via the same FNV striping `ShardedIndex`
  and the kvevents pool use; `ZMQSubscriber` subscribes per-partition
  prefixes and swaps them live on reassignment (`resubscribe`).
- **Scatter-gather scoring** (`scorer.py`): `ClusterScorer` fans
  `get_pod_scores_ex` across replicas (local-call and gRPC transports) and
  merges by partition ownership — bit-identical to a single replica when
  all partitions answer, degraded (missing partition = no cache signal for
  its pods) when one is down.
- **Snapshot / warm restart** (`snapshot.py`, `replica.py`): the published
  read view of any index backend serializes to a versioned canonical-CBOR
  file together with the per-(pod, topic) seq watermarks fleethealth
  tracks; a restarted replica imports the view, replays only the seq tail
  (idempotently — floors drop already-applied events), and is warm in
  seconds, reporting `replaying` to /readyz until it is.
"""

from llm_d_kv_cache_manager_tpu.cluster.partition import (  # noqa: F401
    ClusterConfig,
    ReplicaPartitioner,
)
from llm_d_kv_cache_manager_tpu.cluster.replica import (  # noqa: F401
    READY,
    REPLAYING,
    IndexerReplica,
)
from llm_d_kv_cache_manager_tpu.cluster.membership import (  # noqa: F401
    DRAINING,
    JOINING,
    LEFT,
    REASSIGNING,
    SERVING,
    WARMING,
    FleetMembership,
    MembershipConfig,
    PartitionTable,
    ReplicaBinding,
    export_pod_view,
)
from llm_d_kv_cache_manager_tpu.cluster.scorer import (  # noqa: F401
    ClusterScorer,
    GrpcReplicaTransport,
    LocalReplicaTransport,
    ReplicaUnavailable,
)
from llm_d_kv_cache_manager_tpu.cluster.snapshot import (  # noqa: F401
    SNAPSHOT_VERSION,
    Snapshot,
    SnapshotFormatError,
    read_snapshot,
    restore_index,
    seq_counters_from_tracker,
    write_snapshot,
)

__all__ = [
    "ClusterConfig",
    "ClusterScorer",
    "DRAINING",
    "FleetMembership",
    "GrpcReplicaTransport",
    "IndexerReplica",
    "JOINING",
    "LEFT",
    "LocalReplicaTransport",
    "MembershipConfig",
    "PartitionTable",
    "READY",
    "REASSIGNING",
    "REPLAYING",
    "ReplicaBinding",
    "ReplicaPartitioner",
    "ReplicaUnavailable",
    "SERVING",
    "WARMING",
    "export_pod_view",
    "SNAPSHOT_VERSION",
    "Snapshot",
    "SnapshotFormatError",
    "read_snapshot",
    "restore_index",
    "seq_counters_from_tracker",
    "write_snapshot",
]

"""Index snapshot / warm-restart: serialize the published read view.

A restarted indexer replica is useless until it re-learns the fleet's
placement, which without help takes as long as the engines take to re-store
their chains (minutes of degraded routing — the exact failure mode the
ROADMAP's "scale out the indexer itself" item names). This module makes a
restart a two-step warm-up measured in seconds:

1. **Snapshot.** `write_snapshot` serializes any backend's
   `Index.export_view` projection plus the per-(pod, topic) wire-seq
   watermarks the fleet-health tracker already maintains
   (`FleetHealthTracker.seq_snapshot`) into a versioned file. The encoding
   is the repo's canonical CBOR subset (kvblock/hashing.py — the same
   shortest-form rules the block-hash payloads use), so the snapshot needs
   no serialization dependency and round-trips bit-exactly.
2. **Warm restart.** `read_snapshot` + `Index.import_view` rebuild the
   read state; the seq watermarks become the event pool's replay floors
   (`EventPool.set_seq_floors`), so replaying the retained event tail is
   idempotent — anything at-or-below its floor is already inside the
   imported view and drops as a no-op, anything newer applies normally.

The file is self-describing: magic + version up front, hard error on
mismatch (`SnapshotFormatError`). Writes are atomic (tmp + rename) so a
crash mid-snapshot can never leave a torn file for the next restart.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.hashing import fnv64a
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import Index, IndexView
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import base_pod_identifier
from llm_d_kv_cache_manager_tpu.utils import cbor
from llm_d_kv_cache_manager_tpu.utils import logging as kvlog

logger = kvlog.get_logger("cluster.snapshot")

SNAPSHOT_MAGIC = b"KVTPUSNAP"
# Version 2 appends a little-endian FNV-1a 64 checksum of the CBOR body
# after the document, so a torn write or bit-flipped file fails LOUDLY as
# SnapshotFormatError instead of warm-restarting a silently corrupt index.
# Version-1 files (no checksum) still load.
SNAPSHOT_VERSION = 2


class SnapshotFormatError(ValueError):
    """Bad magic, unknown version, or malformed CBOR in a snapshot file."""


@dataclass
class Snapshot:
    version: int
    created_ts: float
    # (bare pod identifier, topic) -> last wire seq applied to the view.
    seq_counters: Dict[Tuple[str, str], int]
    view: IndexView

    def seq_floors(self) -> Dict[Tuple[str, str], int]:
        """The counters in `EventPool.set_seq_floors` form (same shape —
        named for the consumer)."""
        return dict(self.seq_counters)


# -- canonical CBOR subset codec ---------------------------------------------
# The codec itself lives in utils/cbor.py (shared with federation/digest.py);
# the snapshot owns only its magic/version framing and error type. Byte
# output is unchanged by the extraction — pinned by the round-trip tests.


# -- document shape -----------------------------------------------------------
# [version, created_ts,
#  [[pod, topic, seq], ...],
#  [[model, chunk_hash, [[pod, tier], ...]], ...],
#  [[engine_model, engine_hash, request_model, request_hash], ...]]
# Version 2 appends u64-LE FNV-1a 64 of the CBOR document bytes (the bytes
# between the magic and the checksum) after the document.


def encode_snapshot(
    view: IndexView,
    seq_counters: Dict[Tuple[str, str], int],
    created_ts: Optional[float] = None,
) -> bytes:
    if created_ts is None:
        created_ts = time.time()
    doc = [
        SNAPSHOT_VERSION,
        float(created_ts),
        [[pod, topic, seq] for (pod, topic), seq in sorted(seq_counters.items())],
        [[model, h, [[p, t] for p, t in pods]] for model, h, pods in view.entries],
        [list(row) for row in view.engine_map],
    ]
    body = bytearray()
    cbor.encode_into(doc, body)
    out = bytearray(SNAPSHOT_MAGIC)
    out += body
    out += fnv64a(bytes(body)).to_bytes(8, "little")
    return bytes(out)


def decode_snapshot(data: bytes) -> Snapshot:
    if not data.startswith(SNAPSHOT_MAGIC):
        raise SnapshotFormatError("not a KVTPU index snapshot (bad magic)")
    try:
        doc, end = cbor.decode(data, len(SNAPSHOT_MAGIC))
    except cbor.CborDecodeError as e:
        raise SnapshotFormatError(str(e)) from None
    if not isinstance(doc, list) or len(doc) != 5:
        raise SnapshotFormatError("malformed snapshot document")
    version = doc[0]
    if version == 1:
        # Pre-integrity snapshots carry no checksum; the document must
        # consume the whole file.
        if end != len(data):
            raise SnapshotFormatError(f"{len(data) - end} trailing byte(s)")
    elif version == SNAPSHOT_VERSION:
        trailing = len(data) - end
        if trailing != 8:
            raise SnapshotFormatError(
                "missing or malformed snapshot checksum "
                f"({trailing} trailing byte(s), expected 8)"
            )
        expected = int.from_bytes(data[end:], "little")
        actual = fnv64a(bytes(data[len(SNAPSHOT_MAGIC):end]))
        if actual != expected:
            raise SnapshotFormatError(
                "snapshot checksum mismatch (torn or bit-flipped file) — "
                "refusing to warm-restart from a corrupt index view"
            )
    else:
        raise SnapshotFormatError(
            f"unsupported snapshot version {version} "
            f"(this build reads versions 1..{SNAPSHOT_VERSION})"
        )
    seq_counters = {(pod, topic): seq for pod, topic, seq in doc[2]}
    view = IndexView(
        entries=[
            (model, h, tuple((p, t) for p, t in pods))
            for model, h, pods in doc[3]
        ],
        engine_map=[tuple(row) for row in doc[4]],
    )
    return Snapshot(
        version=version, created_ts=doc[1], seq_counters=seq_counters, view=view
    )


# -- file + tracker plumbing --------------------------------------------------


def seq_counters_from_tracker(tracker) -> Dict[Tuple[str, str], int]:
    """Flatten `FleetHealthTracker.seq_snapshot()` into snapshot form.

    The tracker keys records by DP-rank-qualified identity ("pod@dp0"),
    but the wire seq is per PUBLISHER TOPIC — all ranks of a pod interleave
    one counter — so the floor for (bare pod, topic) is the max across its
    rank records: everything at-or-below it reached the view through some
    rank's batch.
    """
    floors: Dict[Tuple[str, str], int] = {}
    for pod, topics in tracker.seq_snapshot().items():
        base = base_pod_identifier(pod)
        for topic, seq in topics.items():
            key = (base, topic)
            if seq > floors.get(key, -1):
                floors[key] = seq
    return floors


def write_snapshot(
    path: str,
    index: Index,
    seq_counters: Optional[Dict[Tuple[str, str], int]] = None,
    created_ts: Optional[float] = None,
) -> dict:
    """Export `index` and write the snapshot atomically. Returns a small
    stats dict (the /cluster/snapshot response body)."""
    view = index.export_view()
    data = encode_snapshot(view, seq_counters or {}, created_ts=created_ts)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)
    stats = {
        "path": path,
        "bytes": len(data),
        "keys": len(view.entries),
        "pod_entries": view.entry_count(),
        "engine_mappings": len(view.engine_map),
        "seq_counters": len(seq_counters or {}),
        "version": SNAPSHOT_VERSION,
    }
    logger.info(
        "snapshot written: %s (%d keys, %d pod entries, %d bytes)",
        path, stats["keys"], stats["pod_entries"], stats["bytes"],
    )
    return stats


def read_snapshot(path: str) -> Snapshot:
    with open(path, "rb") as f:
        return decode_snapshot(f.read())


def restore_index(index: Index, snapshot: Snapshot) -> int:
    """Import a snapshot's view into a (fresh) index. Returns pod entries
    imported. The caller owns the rest of the warm restart — seq floors,
    tail replay, readiness state (`cluster/replica.py`)."""
    return index.import_view(snapshot.view)


SNAPSHOT_FIELDS: List[str] = [
    "version", "created_ts", "seq_counters", "entries", "engine_map",
]

"""Scatter-gather scoring across indexer replicas.

`ClusterScorer` is the router-facing front of the replicated control plane:
it fans one `get_pod_scores_ex` call across every live replica, merges the
per-partition answers, and degrades — never stalls — when a replica is
down.

**Merge rule.** Partitioning assigns each pod's event stream to exactly one
replica (cluster/partition.py), and `LongestPrefixScorer` accumulates each
pod's score from that pod's entries alone, so replica R's answer for the
pods it owns is exactly what the monolithic indexer would compute for them.
The merge is therefore a disjoint union keyed by ownership: pod P's score,
matched-prefix length, and missing tail come from replica
`partitioner.replica_for(P)` and nowhere else (a stray entry on a
non-owning replica — possible mid-reassignment — can never override the
owner). The prompt's block-hash chain is derivation-side and identical on
every replica; the first successful reply supplies it. With all partitions
answering, the merged result is bit-identical to a single-replica run over
the same event stream — pinned by tests/test_cluster.py.

**Degradation.** A replica that errors or misses the fan-out deadline
contributes nothing: the pods it owns simply carry no cache signal this
request (the same explicit no-signal contract fleet-health degradation
uses), and the router's load fallback covers them. Replica liveness reuses
the fleethealth state machine with replica ids in place of pods: successful
responses stamp liveness, silent replicas decay healthy → suspect → stale,
and stale replicas are skipped entirely — one probe per
`replica_stale_after_s` window rather than a timeout on every request.
"""

from __future__ import annotations

import json
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Tuple

from llm_d_kv_cache_manager_tpu import obs
from llm_d_kv_cache_manager_tpu.cluster.partition import (
    ClusterConfig,
    ReplicaPartitioner,
)
from llm_d_kv_cache_manager_tpu.fleethealth import (
    STALE,
    FleetHealthConfig,
    FleetHealthTracker,
)
from llm_d_kv_cache_manager_tpu.kvcache.indexer import PodScores
from llm_d_kv_cache_manager_tpu.metrics import collector as metrics
from llm_d_kv_cache_manager_tpu.utils import logging as kvlog

logger = kvlog.get_logger("cluster.scorer")


class ReplicaUnavailable(Exception):
    """Transport-level failure talking to one replica (degrade, don't fail)."""


class LocalReplicaTransport:
    """In-process replica: wraps an Indexer (or IndexerReplica.indexer)."""

    def __init__(self, indexer):
        self.indexer = indexer

    def get_pod_scores_ex(
        self, prompt: str, model_name: str, pod_identifiers, lora_id=None
    ) -> PodScores:
        return self.indexer.get_pod_scores_ex(
            prompt, model_name, pod_identifiers, lora_id=lora_id
        )

    def score_many(self, requests) -> List[PodScores]:
        """Batched read path against the wrapped indexer (one amortized
        pass instead of N single calls)."""
        return self.indexer.score_many(requests)

    # -- carrier-propagating forms (obs/carrier.py) ------------------------
    # The scatter-gather runs transports on executor threads, so even an
    # in-process replica loses the caller's thread-local trace; adopting
    # the carrier re-links its root trace to the caller's trace id, and
    # the exported spans ride back exactly like a gRPC reply's — one
    # assembly path for both transports.

    def get_pod_scores_ex_traced(
        self, prompt, model_name, pod_identifiers, lora_id=None, carrier=None
    ):
        with obs.adopt(carrier) as adoption:
            ps = self.indexer.get_pod_scores_ex(
                prompt, model_name, pod_identifiers, lora_id=lora_id
            )
        return ps, obs.export_trace(adoption.trace)

    def score_many_traced(self, requests, carrier=None):
        with obs.adopt(carrier) as adoption:
            results = self.indexer.score_many(requests)
        payload = obs.export_trace(adoption.trace)
        return results, ([payload] if payload is not None else [])


class GrpcReplicaTransport:
    """Remote replica over `kvtpu.api.v1.IndexerService/GetPodScoresEx`.

    The Ex method returns the scores PLUS match_blocks/block_hashes as a
    JSON payload (api/grpc_server.py — same no-protoc generic-handler
    pattern as ExplainScores), which is what the merge needs. Connection
    construction is lazy so building a cluster config never blocks on an
    unreachable peer.
    """

    def __init__(self, target: str, timeout_s: float = 1.0):
        self.target = target
        self.timeout_s = timeout_s
        self._client = None

    def _ensure_client(self):
        if self._client is None:
            from llm_d_kv_cache_manager_tpu.api.grpc_server import (
                IndexerGrpcClient,
            )

            self._client = IndexerGrpcClient(self.target, timeout_s=self.timeout_s)
        return self._client

    @staticmethod
    def _to_pod_scores(payload: dict) -> PodScores:
        return PodScores(
            scores=dict(payload.get("scores", {})),
            match_blocks={
                p: int(n) for p, n in payload.get("match_blocks", {}).items()
            },
            block_hashes=[int(h) for h in payload.get("block_hashes", [])],
        )

    def get_pod_scores_ex(
        self, prompt: str, model_name: str, pod_identifiers, lora_id=None
    ) -> PodScores:
        return self.get_pod_scores_ex_traced(
            prompt, model_name, pod_identifiers, lora_id=lora_id
        )[0]

    def get_pod_scores_ex_traced(
        self, prompt, model_name, pod_identifiers, lora_id=None, carrier=None
    ):
        """Carrier-propagating form: the carrier rides the gRPC metadata,
        the replica runs its stages under the caller's trace id, and its
        span tuples come back as the reply's `trace` field (returned
        separately so the merge never sees it)."""
        import grpc

        try:
            payload = self._ensure_client().get_pod_scores_ex(
                prompt, model_name, pod_identifiers, lora_id=lora_id,
                carrier=carrier,
            )
        except (grpc.RpcError, json.JSONDecodeError, OSError) as e:
            raise ReplicaUnavailable(f"{self.target}: {e}") from e
        return self._to_pod_scores(payload), payload.get("trace")

    def score_many(self, requests) -> List[PodScores]:
        """Batched read path over the streaming `ScorePodsBulk` endpoint:
        the whole batch rides one gRPC stream (the server micro-batches it
        through `Indexer.score_many`), so a replica is crossed once per
        BATCH, not once per request."""
        return self.score_many_traced(requests)[0]

    def score_many_traced(self, requests, carrier=None):
        import grpc

        traces: List[dict] = []
        try:
            payloads = self._ensure_client().score_pods_bulk(
                [
                    {
                        "prompt": r.prompt,
                        "model_name": r.model_name,
                        "pod_identifiers": list(r.pod_identifiers),
                        "lora_id": r.lora_id,
                    }
                    for r in requests
                ],
                carrier=carrier,
                trace_sink=traces,
            )
        except (grpc.RpcError, json.JSONDecodeError, OSError) as e:
            raise ReplicaUnavailable(f"{self.target}: {e}") from e
        if len(payloads) != len(requests):
            raise ReplicaUnavailable(
                f"{self.target}: bulk stream returned {len(payloads)} "
                f"results for {len(requests)} requests"
            )
        return [self._to_pod_scores(p) for p in payloads], traces

    def close(self) -> None:
        if self._client is not None:
            self._client.close()
            self._client = None


class ClusterScorer:
    """N replicas behind one `get_pod_scores` — the router's single front."""

    def __init__(
        self,
        transports: Sequence[object],
        partitioner: Optional[ReplicaPartitioner] = None,
        config: Optional[ClusterConfig] = None,
        clock=time.monotonic,
    ):
        if not transports:
            raise ValueError("ClusterScorer needs at least one transport")
        self.config = config or ClusterConfig(num_replicas=len(transports))
        if len(transports) != self.config.num_replicas:
            raise ValueError(
                f"{len(transports)} transports for "
                f"{self.config.num_replicas} replicas"
            )
        self.transports = list(transports)
        self.partitioner = partitioner or ReplicaPartitioner(len(transports))
        # Replica liveness: the fleethealth state machine verbatim, with
        # replica names as the tracked identities. auto_quarantine off —
        # there is no index to purge; exclusion happens at fan-out time.
        self.health = FleetHealthTracker(
            FleetHealthConfig(
                suspect_after_s=self.config.replica_suspect_after_s,
                stale_after_s=self.config.replica_stale_after_s,
                auto_quarantine=False,
            ),
            clock=clock,
        )
        self.clock = clock
        self._executor = ThreadPoolExecutor(
            max_workers=len(transports), thread_name_prefix="cluster-scatter"
        )
        # Monotonic per-instance counters (status surface; the Prometheus
        # counterpart is kvcache_replica_scatter_errors_total).
        self.scatter_calls = 0
        self.scatter_errors = 0

    @staticmethod
    def replica_name(replica_id: int) -> str:
        return f"replica-{replica_id}"

    def close(self) -> None:
        self._executor.shutdown(wait=False)
        for t in self.transports:
            close = getattr(t, "close", None)
            if close is not None:
                close()

    # -- read path ---------------------------------------------------------

    def get_pod_scores(
        self, prompt: str, model_name: str, pod_identifiers, lora_id=None
    ) -> Dict[str, float]:
        return self.get_pod_scores_ex(
            prompt, model_name, pod_identifiers, lora_id=lora_id
        ).scores

    def get_pod_scores_ex(
        self, prompt: str, model_name: str, pod_identifiers, lora_id=None
    ) -> PodScores:
        with obs.request(
            "cluster.get_pod_scores", {"replicas": len(self.transports)}
        ) as trace:
            return self._scatter_gather(
                prompt, model_name, pod_identifiers, lora_id, trace
            )

    def score_many(self, requests) -> List[PodScores]:
        """Batched scatter-gather: ONE fan-out wave covers the whole
        router batch — each live replica is crossed once per batch (its
        transport's `score_many`), and every item's answer merges under
        the same ownership-keyed rule as `get_pod_scores_ex`. Results are
        bit-identical to per-request scatter-gather over the same state
        (pinned by tests/test_score_many.py at N=2 replicas); a replica
        that fails or misses the deadline contributes no signal to ANY
        item of this batch — the per-partition no-signal degradation,
        batch-scoped."""
        if not requests:
            return []
        requests = list(requests)
        with obs.request(
            "cluster.score_many",
            {"replicas": len(self.transports), "batch": len(requests)},
        ) as trace:
            replies = self._fan_out(
                trace, "score_many", "score_many_traced", requests,
            )
            t_merge = time.perf_counter()
            merged = [
                self._merge([(rid, reply[i]) for rid, reply in replies])
                for i in range(len(requests))
            ]
            obs.record_into(trace, "cluster.merge", t_merge, time.perf_counter())
            return merged

    def _fan_out(self, trace, method, traced_method, *args):
        """One scatter wave: submit the call to every live replica, gather
        under the fan-out deadline, degrade per replica. When the caller
        has a trace, its carrier rides to every replica that supports the
        traced transport form and the replies' span payloads are grafted
        back under per-replica `cluster.rpc` hop spans — the recorder
        then holds ONE cross-process tree for the request."""
        self.scatter_calls += 1
        targets = self._live_replicas()
        carrier = obs.current_carrier() if trace is not None else None

        def call(rid: int):
            transport = self.transports[rid]
            traced = (
                getattr(transport, traced_method, None)
                if carrier is not None else None
            )
            t0 = time.perf_counter()
            if traced is not None:
                result, remote = traced(*args, carrier=carrier)
            else:
                result = getattr(transport, method)(*args)
                remote = None
            return result, remote, t0, time.perf_counter()

        t_fan = time.perf_counter()
        futures = [
            (rid, self._executor.submit(call, rid)) for rid in targets
        ]
        deadline = time.perf_counter() + self.config.scatter_timeout_s
        replies = []
        grafts = []
        degraded: List[int] = []
        for rid, fut in futures:
            budget = max(0.0, deadline - time.perf_counter())
            try:
                result, remote, t0c, t1c = fut.result(timeout=budget)
            except Exception as e:  # noqa: BLE001 - degrade per replica
                fut.cancel()
                self._observe_failure(rid, e)
                degraded.append(rid)
                continue
            self._observe_success(rid)
            replies.append((rid, result))
            if carrier is not None:
                grafts.append((rid, remote, t0c, t1c))
        # One fan-out window for the whole wave, then per-replica rpc hop
        # spans with the replicas' own stages grafted inside them. Which
        # replicas participated/degraded rides in the trace meta (ids are
        # data, never metric labels — cardinality stays bounded).
        obs.record_into(trace, "cluster.fanout", t_fan, time.perf_counter())
        for rid, remote, t0c, t1c in grafts:
            if isinstance(remote, list):
                payloads = [p for p in remote if p] or [None]
            else:
                payloads = [remote]
            for k, payload in enumerate(payloads):
                obs.graft_remote(
                    trace, payload, t0c, t1c, hop="cluster.rpc", depth=2,
                    add_hop=(k == 0),
                )
            obs.annotate("rpc_replicas", self.replica_name(rid))
        if trace is not None and getattr(trace, "meta", None) is not None:
            trace.meta["degraded_replicas"] = degraded
        if degraded:
            kvlog.trace(
                logger,
                "scatter-gather degraded: replicas %s contributed no signal",
                degraded,
            )
        return replies

    def _scatter_gather(
        self, prompt, model_name, pod_identifiers, lora_id, trace
    ) -> PodScores:
        replies = self._fan_out(
            trace, "get_pod_scores_ex", "get_pod_scores_ex_traced",
            prompt, model_name, pod_identifiers, lora_id,
        )
        t_merge = time.perf_counter()
        merged = self._merge(replies)
        obs.record_into(trace, "cluster.merge", t_merge, time.perf_counter())
        return merged

    def _live_replicas(self) -> List[int]:
        """All replicas except stale ones — with the carve-out that a stale
        replica is still probed once per refresh of its state (otherwise
        nothing could ever mark it healthy again)."""
        out = []
        for rid in range(len(self.transports)):
            name = self.replica_name(rid)
            if self.health.state_of(name) != STALE:
                out.append(rid)
            else:
                rec = self.health.summary()["pods"].get(name, {})
                # Probe a stale replica at most once per stale window.
                age = rec.get("last_event_age_s")
                if age is not None and (
                    age % self.config.replica_stale_after_s
                ) < self.config.scatter_timeout_s:
                    out.append(rid)
        return out or list(range(len(self.transports)))

    def _observe_success(self, rid: int) -> None:
        self.health.observe_batch(
            self.replica_name(rid), "scatter", None, self.clock()
        )

    def _observe_failure(self, rid: int, e: Exception) -> None:
        self.scatter_errors += 1
        metrics.count_scatter_error()
        # A failing replica provides no liveness evidence — the tracker's
        # quiet-stream windows do the demotion; the failure count is kept
        # on the record like a decode failure (stream alive but useless).
        self.health.observe_decode_failure(self.replica_name(rid))
        logger.warning(
            "replica %d scatter failed (%s): its partition carries no "
            "cache signal for this request", rid, e,
        )

    def _merge(self, replies: List[Tuple[int, PodScores]]) -> PodScores:
        merged = PodScores()
        replica_for = self.partitioner.replica_for
        for rid, ps in replies:
            if not merged.block_hashes and ps.block_hashes:
                merged.block_hashes = ps.block_hashes
            for pod, score in ps.scores.items():
                if replica_for(pod) == rid:
                    merged.scores[pod] = score
            for pod, n in ps.match_blocks.items():
                if replica_for(pod) == rid:
                    merged.match_blocks[pod] = n
        return merged

    # -- introspection -----------------------------------------------------

    def status(self) -> dict:
        """Cluster-status document (/cluster/status, gRPC ClusterStatus)."""
        summary = self.health.summary()
        replicas = {}
        for rid in range(len(self.transports)):
            name = self.replica_name(rid)
            rec = summary["pods"].get(name)
            replicas[name] = {
                "state": rec["state"] if rec else "healthy",
                "last_response_age_s": (
                    rec["last_event_age_s"] if rec else None
                ),
                "failures": rec["decode_failures"] if rec else 0,
                "transport": type(self.transports[rid]).__name__,
            }
        return {
            "partitioner": self.partitioner.as_dict(),
            "replicas": replicas,
            "scatter_calls": self.scatter_calls,
            "scatter_errors": self.scatter_errors,
            "scatter_timeout_s": self.config.scatter_timeout_s,
        }

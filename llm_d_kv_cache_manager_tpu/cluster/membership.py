"""Elastic fleet membership: pod join/leave as a first-class subsystem.

PR 7 left the gap this module closes: partition reassignment on
join/leave was "a config change" with nothing orchestrating it. An
elastic fleet — the saturation answer the qps ladder demands — needs
three things no config change provides:

- **Warm-before-serve.** A pod that joins cold is a hit-rate crater: the
  router either avoids it (no cache signal → it never warms) or floods it
  (least-loaded fallback → every request recomputes). The join sequence
  replicates the currently-hot prefixes (placement/ popularity tracker →
  prefetch/warm plane, the same jobs `HotPrefixReplicator` emits) BEFORE
  the pod enters the serving set, so its first routed request already
  finds the shared system prompts resident.
- **Live partition handoff, exactly-once.** Replicated indexers own
  disjoint slices of the fleet's event streams; membership changes move
  slices between replicas with a two-phase handoff built entirely from
  existing machinery: pause (ownership override → nobody applies the
  stream; the delivery-seam journal keeps the bytes), transfer (the old
  owner drains, its per-topic seq watermark is captured, the pod's index
  entries move via `export_view`/`import_view`, `remove_pod` clears the
  old owner), then commit (the new owner installs the watermarks as seq
  floors, replays the journal tail through NORMAL ingest — floors make
  double-delivery a no-op — and takes ownership; ZMQ topic filters
  refresh through `resubscribe`). No event is double-applied (floors) or
  lost (journal covers the pause window), and mid-handoff the ownership
  table answers None for the pod, so the scatter-gather merge trusts
  NEITHER replica's answer for it — zero stale-partition scores by
  construction, the same explicit no-signal degradation the cluster
  scorer already uses for a dead replica.
- **Drained departure.** Leave is the fault path made graceful: the pod
  stops being routable the moment draining starts (`serving_pods`
  excludes every non-SERVING phase), its stream drains, and its index
  entries quarantine through the same bulk `remove_pod` the fleet-health
  tracker uses for crashes.

Every phase transition is counted in
``kvcache_membership_transitions_total{phase}`` (fixed vocabulary below).
Like fleethealth, the orchestrator is thread-safe, clock-injectable sync
code with no background threads: benches and tests drive it
deterministically; a deployment calls it from its operator loop.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

from llm_d_kv_cache_manager_tpu.cluster.partition import ReplicaPartitioner
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import IndexView
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import base_pod_identifier
from llm_d_kv_cache_manager_tpu.metrics import collector as metrics
from llm_d_kv_cache_manager_tpu.utils import logging as kvlog

logger = kvlog.get_logger("cluster.membership")

# Membership phases — the FIXED vocabulary of the
# kvcache_membership_transitions_total `phase` label (bounded by
# construction; enforced by tests/test_metrics_hygiene.py).
JOINING = "joining"          # roster entry, onboarding seams
WARMING = "warming"          # hot prefixes replicating, NOT routable yet
REASSIGNING = "reassigning"  # partition handoff in flight
SERVING = "serving"          # routable member
DRAINING = "draining"        # leaving: unroutable, stream draining
LEFT = "left"                # departed, entries quarantined
PHASES = (JOINING, WARMING, REASSIGNING, SERVING, DRAINING, LEFT)


@dataclass
class MembershipConfig:
    # Warm-before-serve: how many of the popularity tracker's hottest
    # chains are replicated to a joining pod, and the minimum hotness a
    # chain needs to be worth shipping. 0 top-k disables warming.
    warm_top_k: int = 8
    warm_hotness_threshold: float = 0.0
    # When True (default) a join without a warm plane still gates through
    # WARMING (with zero jobs) — the phase sequence stays uniform for the
    # metrics/status surfaces. The gate itself is structural either way:
    # a pod is routable only in SERVING.
    require_warm: bool = True


class PartitionTable:
    """Ownership table: FNV-hash default with explicit overrides.

    A drop-in for `ReplicaPartitioner` wherever ownership is READ
    (`ClusterScorer._merge`, event-pool gates, topic filters), plus the
    write operations membership needs: `set_owner` overrides a pod's
    owner (None = paused mid-handoff — no replica owns the stream and the
    scatter-gather merge trusts no replica's answer for the pod), and
    `clear_override` returns it to the hash default.

    One table is SHARED by every replica in the process; per-replica
    views come from `gate(rid)` (an `EventPool.message_filter`) and
    `topic_filters(rid, pods)`.
    """

    def __init__(self, num_replicas: int):
        self._hash = ReplicaPartitioner(num_replicas)
        self.num_replicas = num_replicas
        self._mu = threading.Lock()
        self._overrides: Dict[str, Optional[int]] = {}

    # -- reads (ReplicaPartitioner-compatible) -----------------------------

    def replica_for(self, pod_identifier: str) -> Optional[int]:
        """Owning replica, or None while the pod's stream is paused
        mid-handoff (callers comparing `replica_for(p) == rid` then match
        no replica — exactly the no-signal behavior handoff needs)."""
        base = base_pod_identifier(pod_identifier)
        with self._mu:
            if base in self._overrides:
                return self._overrides[base]
        return self._hash.replica_for(base)

    def hash_replica_for(self, pod_identifier: str) -> int:
        """The override-free FNV default (where a pod's stream homes when
        no handoff has moved it)."""
        return self._hash.replica_for(pod_identifier)

    def gate(self, replica_id: int) -> Callable:
        """`EventPool.message_filter` for one replica's pool."""
        def accepts(msg) -> bool:
            return self.replica_for(msg.pod_identifier) == replica_id
        return accepts

    def topic_filters(
        self, replica_id: int, pod_identifiers: Sequence[str]
    ) -> List[str]:
        """ZMQ SUB prefixes for one replica's owned slice of the roster
        (feed to `ZMQSubscriber.resubscribe` after membership changes)."""
        owned = sorted(
            base_pod_identifier(p)
            for p in pod_identifiers
            if self.replica_for(p) == replica_id
        )
        return [f"kv@{pod}@" for pod in dict.fromkeys(owned)]

    def partition_map(
        self, pod_identifiers: Sequence[str]
    ) -> Dict[Optional[int], List[str]]:
        out: Dict[Optional[int], List[str]] = {
            r: [] for r in range(self.num_replicas)
        }
        for pod in sorted({base_pod_identifier(p) for p in pod_identifiers}):
            out.setdefault(self.replica_for(pod), []).append(pod)
        return out

    def as_dict(self) -> dict:
        with self._mu:
            overrides = dict(self._overrides)
        return {
            "num_replicas": self.num_replicas,
            "overrides": {
                pod: rid for pod, rid in sorted(overrides.items())
            },
        }

    # -- writes (membership only) ------------------------------------------

    def set_owner(self, pod_identifier: str, replica_id: Optional[int]) -> None:
        base = base_pod_identifier(pod_identifier)
        if replica_id is not None and not (
            0 <= replica_id < self.num_replicas
        ):
            raise ValueError(
                f"replica {replica_id} outside [0, {self.num_replicas})"
            )
        with self._mu:
            self._overrides[base] = replica_id

    def clear_override(self, pod_identifier: str) -> None:
        with self._mu:
            self._overrides.pop(base_pod_identifier(pod_identifier), None)


@dataclass
class ReplicaBinding:
    """What membership needs to touch one replica during a handoff: its
    partition-gated event pool, its index, and (optionally) a callable
    applying a fresh ZMQ filter list (`ZMQSubscriber.resubscribe`, or the
    pool's subscriber via `EventPool.config.topic_filters` on restart)."""

    replica_id: int
    event_pool: object
    index: object
    resubscribe: Optional[Callable[[List[str]], None]] = None


def export_pod_view(index, pod_identifier: str) -> IndexView:
    """Project ONE pod's slice out of an index's exported view.

    Rows keep only the moved pod's (pod, tier) entries (DP-rank-qualified
    identities move with their base pod, matching `remove_pod`); the
    engine-key map keeps rows whose request key survives — everything the
    new owner needs to score the pod, nothing that would alias another
    replica's partition.
    """
    base = base_pod_identifier(pod_identifier)
    full = index.export_view()
    entries = []
    kept_keys = set()
    for model_name, chunk_hash, pods in full.entries:
        kept = tuple(
            (pod, tier) for pod, tier in pods
            if base_pod_identifier(pod) == base
        )
        if kept:
            entries.append((model_name, chunk_hash, kept))
            kept_keys.add((model_name, chunk_hash))
    engine_map = [
        row for row in full.engine_map if (row[2], row[3]) in kept_keys
    ]
    return IndexView(entries=entries, engine_map=engine_map)


class FleetMembership:
    """Pod lifecycle orchestrator: join / leave / partition handoff."""

    def __init__(
        self,
        config: Optional[MembershipConfig] = None,
        table: Optional[PartitionTable] = None,
        replicas: Sequence[ReplicaBinding] = (),
        fleet_health=None,
        load_tracker=None,
        popularity=None,
        warm_submit: Optional[Callable] = None,
        watermark_fn: Optional[Callable[[str], Dict]] = None,
        journal_fn: Optional[Callable[[], Sequence]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.config = config or MembershipConfig()
        self.table = table
        self.replicas = {b.replica_id: b for b in replicas}
        # fleethealth.FleetHealthTracker: departures quarantine through it
        # (stale transition + bulk index purge) so leave and crash share
        # one code path. Optional — leave falls back to raw remove_pod.
        self.fleet_health = fleet_health
        # fleethealth.load.PodLoadTracker: a joining pod gets an explicit
        # idle baseline so the load-blend policy treats it as available
        # the moment it serves (no report ≠ repelled, but be explicit).
        self.load_tracker = load_tracker
        # placement.ChainPopularityTracker (duck-typed: hot_chains): the
        # warm-before-serve source. warm_submit(pod, chain) ships one hot
        # chain to the joining pod (RoutePrefetcher.submit + warm_chain in
        # the benches; an RPC in a deployment) and returns truthiness.
        self.popularity = popularity
        self.warm_submit = warm_submit
        # watermark_fn(pod) -> {(base_pod, topic): last_applied_seq} — the
        # old owner's applied watermark at drain time (the deployment's
        # fleethealth `seq_counters_from_tracker`, the bench's applied-seq
        # map). journal_fn() -> the retained delivery-seam tail (Messages)
        # covering at least the pause window, same contract as
        # warm-restart replay.
        self.watermark_fn = watermark_fn
        self.journal_fn = journal_fn
        self.clock = clock
        # resourcegov.DepartureReaper (optional): leave() fans the
        # departure out to every registered per-pod forget hook after the
        # quarantine/purge, so breaker rows, trust EWMAs, load records
        # and negative-cache entries die with the pod instead of
        # accumulating across churn. Attached by the service wiring.
        self.reaper = None
        self._mu = threading.Lock()
        self._phase: Dict[str, str] = {}
        self._since: Dict[str, float] = {}
        self.stats = {
            "joins": 0, "leaves": 0, "handoffs": 0,
            "warm_jobs_submitted": 0, "entries_moved": 0,
            "journal_replayed": 0, "replay_skipped": 0,
        }

    # -- roster ------------------------------------------------------------

    def phase_of(self, pod_identifier: str) -> Optional[str]:
        with self._mu:
            return self._phase.get(base_pod_identifier(pod_identifier))

    def serving_pods(self) -> List[str]:
        """The routable set — the warm-before-serve gate made structural:
        only SERVING members appear, so a router whose pods_fn consults
        membership cannot route to a pod that is still warming, draining,
        or mid-handoff."""
        with self._mu:
            return sorted(
                p for p, ph in self._phase.items() if ph == SERVING
            )

    def members(self) -> Dict[str, dict]:
        now = self.clock()
        with self._mu:
            return {
                pod: {
                    "phase": ph,
                    "phase_age_s": round(now - self._since[pod], 3),
                }
                for pod, ph in sorted(self._phase.items())
            }

    def bootstrap(self, pod_identifiers: Sequence[str]) -> None:
        """Register an already-running fleet as SERVING members (process
        start / bench init): pods that predate the membership service get
        no join choreography — they are serving by observation."""
        for pod in pod_identifiers:
            self._transition(base_pod_identifier(pod), SERVING)

    def _transition(self, pod: str, phase: str) -> None:
        assert phase in PHASES, phase
        with self._mu:
            old = self._phase.get(pod)
            self._phase[pod] = phase
            self._since[pod] = self.clock()
        metrics.count_membership_transition(phase)
        logger.info("membership: pod %s %s -> %s", pod, old, phase)

    # -- join --------------------------------------------------------------

    def begin_join(self, pod_identifier: str) -> dict:
        """Phase 1 of a join: onboard + start warming. The pod is NOT
        routable yet; the caller executes/awaits the warm jobs (drain the
        prefetch plane) and then calls `finish_join`."""
        pod = base_pod_identifier(pod_identifier)
        with self._mu:
            current = self._phase.get(pod)
        if current is not None and current not in (LEFT,):
            raise ValueError(f"pod {pod} already a member (phase {current})")
        self._transition(pod, JOINING)
        # Onboarding seams: an explicit idle load baseline; fleet health
        # learns the pod lazily from its first event batch (a pod that
        # never stored is healthy by definition — tracker contract).
        if self.load_tracker is not None:
            self.load_tracker.report(pod, queue_depth=0.0, inflight=0.0)
        warm_jobs = 0
        if self.config.require_warm or (
            self.popularity is not None and self.warm_submit is not None
        ):
            self._transition(pod, WARMING)
        if (
            self.popularity is not None
            and self.warm_submit is not None
            and self.config.warm_top_k > 0
        ):
            hot = self.popularity.hot_chains(
                self.config.warm_hotness_threshold
            )
            for chain in hot[: self.config.warm_top_k]:
                if self.warm_submit(pod, chain):
                    warm_jobs += 1
        with self._mu:
            self.stats["joins"] += 1
            self.stats["warm_jobs_submitted"] += warm_jobs
        return {"pod": pod, "phase": self.phase_of(pod),
                "warm_jobs": warm_jobs}

    def finish_join(self, pod_identifier: str) -> dict:
        """Phase 2 of a join: take partition ownership (hash-default home,
        topic filters refreshed) and enter the serving set."""
        pod = base_pod_identifier(pod_identifier)
        current = self.phase_of(pod)
        if current not in (JOINING, WARMING):
            raise ValueError(
                f"pod {pod} not joining (phase {current})"
            )
        stats = {"pod": pod}
        if self.table is not None and self.replicas:
            self._transition(pod, REASSIGNING)
            rid = self.table.hash_replica_for(pod)
            self.table.clear_override(pod)  # hash default IS the owner
            self._refresh_filters()
            stats["owner_replica"] = rid
        self._transition(pod, SERVING)
        return stats

    def join(self, pod_identifier: str) -> dict:
        """Synchronous join (warm jobs submitted, not awaited — callers
        needing a hard warm gate use begin_join / drain / finish_join)."""
        stats = self.begin_join(pod_identifier)
        stats.update(self.finish_join(pod_identifier))
        return stats

    # -- leave -------------------------------------------------------------

    def leave(self, pod_identifier: str) -> dict:
        """Graceful departure: unroutable immediately, stream drained,
        entries quarantined through the fleet-health `remove_pod` path."""
        pod = base_pod_identifier(pod_identifier)
        current = self.phase_of(pod)
        if current != SERVING:
            raise ValueError(f"pod {pod} not serving (phase {current})")
        self._transition(pod, DRAINING)
        owner = (
            self.table.replica_for(pod) if self.table is not None else None
        )
        binding = self.replicas.get(owner)
        if binding is not None:
            binding.event_pool.drain()
        purged = 0
        if self.fleet_health is not None:
            purged = self.fleet_health.quarantine(pod)
        elif binding is not None:
            purged = binding.index.remove_pod(pod)
        if self.table is not None:
            # Departed pods fall back to the hash default (irrelevant
            # until the identity returns) and filters shrink.
            self.table.clear_override(pod)
            self._refresh_filters()
        self._transition(pod, LEFT)
        reaped = 0
        if self.reaper is not None:
            try:
                reaped = sum(self.reaper.reap(pod).values())
            except Exception as e:  # noqa: BLE001 - a reap failure must
                # not fail the departure; the pod is already unroutable
                logger.warning("departure reap failed for %s: %s", pod, e)
        with self._mu:
            self.stats["leaves"] += 1
        return {"pod": pod, "purged_entries": purged,
                "reaped_rows": reaped}

    # -- partition handoff -------------------------------------------------

    def reassign_pod(
        self, pod_identifier: str, new_owner: int
    ) -> dict:
        """Two-phase handoff of one pod's event stream + index slice.

        Phase 1 — prepare: ownership override goes to None (PAUSED: no
        replica's gate accepts the stream; the scatter-gather merge,
        reading this table, trusts no replica's answer for the pod — a
        stray entry cannot score). The old owner drains, its applied
        watermark is captured, and the pod's index slice moves
        (`export_pod_view` → `import_view`; `remove_pod` clears the old
        owner).

        Phase 2 — commit: the new owner installs the watermark as seq
        floors, ownership flips to it (its gate now accepts the stream),
        the delivery-seam journal replays through NORMAL ingest (floors
        drop everything the moved view already contains — no event
        double-applied; the journal covers the pause window — no event
        lost), the pool drains, floors clear, and both replicas' ZMQ
        filters refresh.
        """
        if self.table is None:
            raise ValueError("reassign_pod needs a PartitionTable")
        pod = base_pod_identifier(pod_identifier)
        old_owner = self.table.replica_for(pod)
        stats = {"pod": pod, "from": old_owner, "to": new_owner}
        if old_owner == new_owner:
            return stats
        old_b = self.replicas.get(old_owner)
        new_b = self.replicas.get(new_owner)
        if new_b is None:
            raise ValueError(f"no binding for replica {new_owner}")
        self._transition(pod, REASSIGNING)

        # Phase 1: pause + drain + capture + move.
        self.table.set_owner(pod, None)
        if old_b is not None:
            old_b.event_pool.drain()
        floors = (
            dict(self.watermark_fn(pod)) if self.watermark_fn is not None
            else {}
        )
        # Only this pod's topics may floor the new owner's ingest.
        floors = {
            key: seq for key, seq in floors.items()
            if base_pod_identifier(key[0]) == pod
        }
        moved = 0
        if old_b is not None:
            view = export_pod_view(old_b.index, pod)
            moved = new_b.index.import_view(view)
            old_b.index.remove_pod(pod)

        # Phase 2: floors + ownership flip + journal replay + resume.
        new_b.event_pool.set_seq_floors(floors)
        self.table.set_owner(pod, new_owner)
        skipped_before = new_b.event_pool.replay_skipped
        replayed = 0
        if self.journal_fn is not None:
            for msg in self.journal_fn():
                if base_pod_identifier(msg.pod_identifier) == pod:
                    new_b.event_pool.add_task(msg)
                    replayed += 1
        new_b.event_pool.drain()
        skipped = new_b.event_pool.replay_skipped - skipped_before
        new_b.event_pool.clear_seq_floors()
        self._refresh_filters()
        prior = self.phase_of(pod)
        if prior == REASSIGNING:
            self._transition(pod, SERVING)
        with self._mu:
            self.stats["handoffs"] += 1
            self.stats["entries_moved"] += moved
            self.stats["journal_replayed"] += replayed
            self.stats["replay_skipped"] += skipped
        stats.update({
            "entries_moved": moved,
            "seq_floors": len(floors),
            "journal_replayed": replayed,
            "replay_skipped": skipped,
        })
        logger.info("partition handoff %s: %s", pod, stats)
        return stats

    def _refresh_filters(self) -> None:
        """Push each replica's current owned-topic list to its subscriber
        (`resubscribe` applies between polls — no rebind)."""
        if self.table is None:
            return
        with self._mu:
            roster = [
                p for p, ph in self._phase.items() if ph != LEFT
            ]
        for binding in self.replicas.values():
            if binding.resubscribe is not None:
                binding.resubscribe(
                    self.table.topic_filters(binding.replica_id, roster)
                )

    # -- introspection -----------------------------------------------------

    def status(self) -> dict:
        with self._mu:
            stats = dict(self.stats)
        return {
            "members": self.members(),
            "serving": self.serving_pods(),
            "partition_table": (
                self.table.as_dict() if self.table is not None else None
            ),
            "config": {
                "warm_top_k": self.config.warm_top_k,
                "warm_hotness_threshold": self.config.warm_hotness_threshold,
                "require_warm": self.config.require_warm,
            },
            "stats": stats,
        }

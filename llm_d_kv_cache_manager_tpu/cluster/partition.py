"""Event-stream partitioning for the replicated indexer control plane.

N indexer replicas act as one logical index by splitting the fleet's event
streams, not by mirroring them: each (pod, dp_rank) topic is owned by
exactly one replica, chosen by the same FNV-1a striping the rest of the
stack already uses (`fnv32a(pod) % S` shards messages inside one pool,
`chunk_hash % S` stripes `ShardedIndex` segments). Striping by the BARE
pod identity (DP-rank suffix stripped) keeps every rank of a pod — and
therefore every index entry the event pool writes for it — inside one
replica, which is what makes the scatter-gather merge rule exact: a pod's
score is a function of that pod's entries only (`LongestPrefixScorer`
accumulates per pod independently), so the replica owning the pod's stream
computes the same score the monolithic indexer would, and the cluster
answer is the union of per-partition answers.

The partitioner is deterministic and stateless — every replica, router,
and bench computes the same map from (num_replicas, pod) with no
coordination service. Reassignment is a config change (new num_replicas /
replica_id) applied through `ZMQSubscriber.resubscribe` plus the event
pool's ownership gate; no process restart.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Sequence

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.hashing import fnv32a
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import base_pod_identifier


@dataclass
class ClusterConfig:
    """Shape of one replica's membership in the logical index.

    Env mapping (api/http_service.py): CLUSTER_REPLICAS, CLUSTER_REPLICA_ID,
    CLUSTER_GRPC_TARGETS (comma-separated), CLUSTER_SNAPSHOT_PATH.
    """

    num_replicas: int = 1
    replica_id: int = 0
    # Peer scoring endpoints for the gRPC scatter-gather transport, indexed
    # by replica id. Empty = local-only (tests, single-process clusters).
    grpc_targets: List[str] = field(default_factory=list)
    # Where this replica writes/loads its warm-restart snapshot. Empty
    # disables the snapshot endpoints.
    snapshot_path: str = ""
    # Scatter-gather fan-out deadline per replica. A replica that cannot
    # answer inside it contributes no cache signal for its partition —
    # degraded routing, never a stalled request.
    scatter_timeout_s: float = 1.0
    # Replica-liveness windows (reuses the fleethealth state machine with
    # replica ids in place of pods): a replica with no successful scatter
    # response for suspect_after_s is still tried; past stale_after_s the
    # fan-out skips it entirely until it answers a probe again.
    replica_suspect_after_s: float = 10.0
    replica_stale_after_s: float = 30.0

    def __post_init__(self):
        if self.num_replicas <= 0:
            raise ValueError(
                f"num_replicas must be positive, got {self.num_replicas}"
            )
        if not 0 <= self.replica_id < self.num_replicas:
            raise ValueError(
                f"replica_id {self.replica_id} outside [0, {self.num_replicas})"
            )


class ReplicaPartitioner:
    """Deterministic (pod, dp_rank)-topic → replica assignment."""

    def __init__(self, num_replicas: int, replica_id: int = 0):
        if num_replicas <= 0:
            raise ValueError(f"num_replicas must be positive, got {num_replicas}")
        if not 0 <= replica_id < num_replicas:
            raise ValueError(
                f"replica_id {replica_id} outside [0, {num_replicas})"
            )
        self.num_replicas = num_replicas
        self.replica_id = replica_id

    def replica_for(self, pod_identifier: str) -> int:
        """Owning replica of a pod's event topics — FNV-1a over the BARE
        pod identity, so "pod@dp3" lands with "pod"."""
        base = base_pod_identifier(pod_identifier)
        return fnv32a(base.encode("utf-8")) % self.num_replicas

    def owns(self, pod_identifier: str) -> bool:
        return self.replica_for(pod_identifier) == self.replica_id

    def accepts(self, msg) -> bool:
        """`EventPool.message_filter` form: True when this replica owns the
        message's pod stream."""
        return self.owns(msg.pod_identifier)

    def topic_filters(self, pod_identifiers: Sequence[str]) -> List[str]:
        """ZMQ SUB prefix filters for the owned slice of a known pod list:
        one "kv@<pod-id>@" per owned pod, sorted for determinism. ZMQ
        filters are prefix matches, so hash ownership cannot be expressed
        directly — enumerate the fleet instead, and fall back to the
        broad "kv@" filter (plus the authoritative `accepts` gate) while
        the fleet is still being discovered."""
        owned = sorted(
            base_pod_identifier(p)
            for p in pod_identifiers
            if self.owns(p)
        )
        return [f"kv@{pod}@" for pod in dict.fromkeys(owned)]

    def partition_map(self, pod_identifiers: Sequence[str]) -> Dict[int, List[str]]:
        """{replica_id: sorted owned pods} over a pod list (status surfaces
        and the docs' partition-map illustration)."""
        out: Dict[int, List[str]] = {r: [] for r in range(self.num_replicas)}
        for pod in sorted(set(base_pod_identifier(p) for p in pod_identifiers)):
            out[self.replica_for(pod)].append(pod)
        return out

    def as_dict(self) -> dict:
        return {
            "num_replicas": self.num_replicas,
            "replica_id": self.replica_id,
        }

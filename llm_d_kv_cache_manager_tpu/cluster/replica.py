"""One member of the replicated indexer control plane.

`IndexerReplica` ties the pieces together for a single process: the
partition gate on its event pool (it digests only the streams it owns), the
snapshot writer, and the warm-restart sequence with its readiness state
machine:

    ready ──crash/restart──▶ replaying ──tail drained──▶ ready

The `replaying` state is first-class and distinct from `unready`
(api/http_service.py maps it to a 503 with its own status string): a
replica replaying its seq tail has a *partially stale* view — routers must
not scatter-gather to it yet, but operators should see "warming up, N
events behind", not a generic failure. A freshly-started replica with an
empty index is `ready` (an empty view is a *correct* view — scores degrade
to no-signal, exactly like a cold cache), which is what keeps readiness
from deadlocking on a quiet fleet.

Warm restart is: import the snapshot view, install the snapshot's
per-(pod, topic) seq watermarks as replay floors on the event pool, feed
the retained event tail through the NORMAL ingest path (floors make
already-applied events no-ops — replay is idempotent by construction),
drain, clear the floors, and flip to ready. Only the tail is re-digested:
warm in seconds instead of the minutes a full fleet re-store takes.
"""

from __future__ import annotations

import time
from typing import Iterable, List, Optional, Sequence

from llm_d_kv_cache_manager_tpu import obs
from llm_d_kv_cache_manager_tpu.cluster.partition import (
    ClusterConfig,
    ReplicaPartitioner,
)
from llm_d_kv_cache_manager_tpu.cluster import snapshot as snapshot_mod
from llm_d_kv_cache_manager_tpu.kvevents.pool import EventPool, EventPoolConfig
from llm_d_kv_cache_manager_tpu.metrics import collector as metrics
from llm_d_kv_cache_manager_tpu.utils import logging as kvlog

logger = kvlog.get_logger("cluster.replica")

READY = "ready"
REPLAYING = "replaying"


class IndexerReplica:
    """Partition-scoped Indexer + EventPool + snapshot/warm-restart."""

    def __init__(
        self,
        indexer,
        config: Optional[ClusterConfig] = None,
        pool_config: Optional[EventPoolConfig] = None,
        health_tracker=None,
        clock=time.time,
    ):
        self.config = config or ClusterConfig()
        self.partitioner = ReplicaPartitioner(
            self.config.num_replicas, self.config.replica_id
        )
        self.indexer = indexer
        self.health = health_tracker if health_tracker is not None else getattr(
            indexer, "fleet_health", None
        )
        self.clock = clock
        self.event_pool = EventPool(
            pool_config,
            indexer.kv_block_index,
            indexer.token_processor,
            health_tracker=self.health,
            message_filter=(
                self.partitioner.accepts
                if self.config.num_replicas > 1
                else None  # single replica: the gate is pure overhead
            ),
        )
        self.state = READY
        self.last_snapshot_ts: Optional[float] = None
        self.last_restart_stats: Optional[dict] = None
        metrics.set_replica_partitions(self.config.num_replicas)
        metrics.count_replica_transition(self.state)

    # -- lifecycle ---------------------------------------------------------

    def start(self, with_subscriber: bool = False) -> None:
        self.event_pool.start(with_subscriber=with_subscriber)

    def shutdown(self) -> None:
        self.event_pool.shutdown()

    def _set_state(self, state: str) -> None:
        if state == self.state:
            return
        old, self.state = self.state, state
        metrics.count_replica_transition(state)
        logger.info(
            "replica %d/%d: %s -> %s",
            self.config.replica_id, self.config.num_replicas, old, state,
        )

    # -- event plane -------------------------------------------------------

    def ingest(self, msg) -> None:
        """Direct delivery seam (benches/tests); production traffic arrives
        through the pool's partition-filtered ZMQ subscriber."""
        self.event_pool.add_task(msg)

    def topic_filters(self, pod_identifiers: Sequence[str]) -> List[str]:
        """ZMQ filter list for this replica's slice of a known fleet; feed
        to `ZMQSubscriber.resubscribe` on reassignment."""
        return self.partitioner.topic_filters(pod_identifiers)

    # -- snapshot / warm restart -------------------------------------------

    def take_snapshot(self, path: Optional[str] = None) -> dict:
        """Drain in-flight events, then write the view + seq watermarks."""
        path = path or self.config.snapshot_path
        if not path:
            raise ValueError("no snapshot path configured")
        self.event_pool.drain()
        seq_counters = (
            snapshot_mod.seq_counters_from_tracker(self.health)
            if self.health is not None
            else {}
        )
        now = self.clock()
        stats = snapshot_mod.write_snapshot(
            path, self.indexer.kv_block_index, seq_counters, created_ts=now
        )
        self.last_snapshot_ts = now
        metrics.set_snapshot_age(0.0)
        return stats

    def warm_restart(
        self, path: Optional[str] = None, tail: Iterable = ()
    ) -> dict:
        """Snapshot-load + seq-tail replay; `replaying` until drained.

        `tail` is the retained event tail (Messages) from whatever journal
        the deployment keeps — the bench retains a bounded ring at the
        delivery seam. Replay rides the normal ingest path: the snapshot's
        floors drop anything already inside the imported view.
        """
        path = path or self.config.snapshot_path
        with obs.request("cluster.warm_restart", {
            "replica": self.config.replica_id,
        }) as trace:
            t0 = time.perf_counter()
            snap = snapshot_mod.read_snapshot(path)
            self._set_state(REPLAYING)
            imported = snapshot_mod.restore_index(
                self.indexer.kv_block_index, snap
            )
            obs.record_into(
                trace, "cluster.snapshot_load", t0, time.perf_counter()
            )
            t1 = time.perf_counter()
            floors = snap.seq_floors()
            self.event_pool.set_seq_floors(floors)
            skipped_before = self.event_pool.replay_skipped
            replayed = 0
            for msg in tail:
                metrics.set_replay_lag(max(0, replayed))
                self.event_pool.add_task(msg)
                replayed += 1
            self.event_pool.drain()
            self.event_pool.clear_seq_floors()
            metrics.set_replay_lag(0)
            obs.record_into(trace, "cluster.replay", t1, time.perf_counter())
            self._set_state(READY)
            stats = {
                "snapshot_path": path,
                "snapshot_created_ts": snap.created_ts,
                "imported_pod_entries": imported,
                "seq_floors": len(floors),
                "tail_messages": replayed,
                "replay_skipped": (
                    self.event_pool.replay_skipped - skipped_before
                ),
                "warm_restart_s": round(time.perf_counter() - t0, 6),
            }
            self.last_restart_stats = stats
            logger.info(
                "warm restart complete: %d entries imported, %d/%d tail "
                "messages were pre-floor no-ops, %.3fs",
                imported, stats["replay_skipped"], replayed,
                stats["warm_restart_s"],
            )
            return stats

    # -- introspection -----------------------------------------------------

    def snapshot_age_s(self) -> Optional[float]:
        if self.last_snapshot_ts is None:
            return None
        return max(0.0, self.clock() - self.last_snapshot_ts)

    def readiness(self) -> dict:
        """The /readyz `replication` section."""
        age = self.snapshot_age_s()
        if age is not None:
            metrics.set_snapshot_age(age)
        return {
            "replica_id": self.config.replica_id,
            "num_replicas": self.config.num_replicas,
            "state": self.state,
            "snapshot_path": self.config.snapshot_path or None,
            "snapshot_age_s": None if age is None else round(age, 3),
            "partition_filtered_events": self.event_pool.filtered_events,
            "replay_skipped": self.event_pool.replay_skipped,
            "last_restart": self.last_restart_stats,
        }

"""kv_connectors: the KV-block data plane (host staging + ICI/DCN transfer).

The reference plans but never implements this component
(/root/reference/kv_connectors/ holds only a .gitkeep; BASELINE.json's north
star requires "a TPU kv_connectors implementation that ships KV blocks
pod-to-pod over ICI/DCN"). This module is that implementation:

- **Host staging tier**: `KVConnector.offload` DMAs a page out of TPU HBM
  into host RAM, registers it with the C++ transfer server
  (kv_connectors/cpp/kv_transfer.cpp), and emits BlockStored(medium="host")
  so the control plane scores the block at the host-tier weight. The
  pipelined form is `offload_async` + `drain_offloads`: the D2H copy is
  dispatched immediately (`copy_to_host_async`, overlapping queued compute)
  and a bounded completion queue pays only the residual sync at drain time.
  `restore` moves blocks back into HBM pages.
- **DCN / cross-pod leg**: `fetch_block`/`fetch_blocks` pull staged blocks
  from another pod's transfer server over TCP (the C++ engine; ctypes
  binding, no pybind11 in this image). The client side is a pooled
  keep-alive `TransferClient`: one persistent connection per peer, a
  multi-block request protocol (one round trip per chain, not per block),
  and bounded connect/read timeouts with retry — a dead peer costs a
  bounded timeout and a `transfer_failures` metric, never a hung socket.
  `KVConnector.onboard` lands fetched blocks in local pages + emits
  BlockStored(medium="hbm").
- **ICI / intra-slice leg**: within one mesh, pages move device-to-device
  with `jax.device_put` / sharding constraints — XLA emits the ICI copies;
  `transfer_ici` wraps this.

Block wire format: raw little-endian bytes of the page pair, header-free —
the hash is the name, sizes come from the engine config on both ends.
"""

from __future__ import annotations

import ctypes
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from llm_d_kv_cache_manager_tpu import obs
from llm_d_kv_cache_manager_tpu.kvevents.events import (
    BlockRemoved,
    BlockStored,
    EventBatch,
)
from llm_d_kv_cache_manager_tpu.metrics import collector as metrics
from llm_d_kv_cache_manager_tpu.utils import logging as kvlog

logger = kvlog.get_logger("kv_connectors")

# Override the transfer-engine library location (absolute path to
# libkvtransfer.so). Takes precedence over the checkout/package locations.
_LIB_ENV = "KVTPU_TRANSFER_LIB"


def _candidate_lib_paths() -> List[str]:
    """Absolute candidate paths for libkvtransfer.so, most specific first.
    Never a bare soname: a bare "libkvtransfer.so" would let a stale copy
    on the system loader path silently shadow the checkout's build."""
    paths = []
    env = os.environ.get(_LIB_ENV)
    if env:
        paths.append(os.path.abspath(env))
    # Repo-checkout layout: <repo>/kv_connectors/cpp/libkvtransfer.so.
    paths.append(os.path.abspath(os.path.join(
        os.path.dirname(__file__), "..", "..", "kv_connectors", "cpp",
        "libkvtransfer.so",
    )))
    # Installed-package layout: the .so shipped alongside this module
    # (importlib.resources resolves the package dir wherever it landed).
    try:
        from importlib import resources

        pkg = resources.files("llm_d_kv_cache_manager_tpu.kv_connectors")
        paths.append(str(pkg / "libkvtransfer.so"))
    except Exception:  # noqa: BLE001 - resources API absent/odd installs
        paths.append(os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "libkvtransfer.so"
        ))
    return paths


def _load_lib() -> Optional[ctypes.CDLL]:
    for path in _candidate_lib_paths():
        if not os.path.exists(path):
            continue
        try:
            lib = ctypes.CDLL(path)
        except OSError as e:
            logger.warning("found %s but could not load it: %s", path, e)
            continue
        _configure_lib(lib)
        logger.info("kv transfer engine loaded from %s", path)
        return lib
    logger.debug(
        "libkvtransfer.so not found (searched %s) — transfer plane disabled",
        _candidate_lib_paths(),
    )
    return None


def _configure_lib(lib: ctypes.CDLL) -> None:
    lib.kvt_server_start.restype = ctypes.c_void_p
    lib.kvt_server_start.argtypes = [ctypes.c_int]
    lib.kvt_server_port.restype = ctypes.c_int
    lib.kvt_server_port.argtypes = [ctypes.c_void_p]
    lib.kvt_server_put.restype = ctypes.c_int
    lib.kvt_server_put.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_uint64,
    ]
    lib.kvt_server_remove.restype = ctypes.c_int
    lib.kvt_server_remove.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.kvt_server_block_count.restype = ctypes.c_uint64
    lib.kvt_server_block_count.argtypes = [ctypes.c_void_p]
    lib.kvt_server_stop.restype = None
    lib.kvt_server_stop.argtypes = [ctypes.c_void_p]
    lib.kvt_fetch.restype = ctypes.c_int64
    lib.kvt_fetch.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
    ]
    # Pooled-client API (this build). Guarded so a stale .so from an older
    # build still serves the single-block legacy path instead of failing
    # at import; the batched paths then degrade to per-block fetches.
    if hasattr(lib, "kvt_fetch_many"):
        lib.kvt_connect.restype = ctypes.c_int
        lib.kvt_connect.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
        lib.kvt_close.restype = None
        lib.kvt_close.argtypes = [ctypes.c_int]
        lib.kvt_fetch_conn.restype = ctypes.c_int64
        lib.kvt_fetch_conn.argtypes = [
            ctypes.c_int, ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_uint64, ctypes.c_int,
        ]
        lib.kvt_fetch_many.restype = ctypes.c_int
        lib.kvt_fetch_many.argtypes = [
            ctypes.c_int, ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ]
    # Integrity ABI (this build): the v2 checksummed wire plus the
    # corruption test hook. Guarded separately so a pre-integrity .so
    # still serves the v1 paths.
    if hasattr(lib, "kvt_fetch_many2"):
        lib.kvt_fetch_many2.restype = ctypes.c_int
        lib.kvt_fetch_many2.argtypes = [
            ctypes.c_int, ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ]
        lib.kvt_server_corrupt.restype = ctypes.c_int
        lib.kvt_server_corrupt.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.kvt_checksum.restype = ctypes.c_uint64
        lib.kvt_checksum.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
        ]


_lib = _load_lib()


def native_available() -> bool:
    return _lib is not None


def client_api_available() -> bool:
    """True when the loaded .so carries the pooled/batched client ABI."""
    return _lib is not None and hasattr(_lib, "kvt_fetch_many")


def integrity_api_available() -> bool:
    """True when the loaded .so carries the v2 checksummed wire."""
    return _lib is not None and hasattr(_lib, "kvt_fetch_many2")


class BlockTransferServer:
    """One pod's block-export endpoint (C++ engine, host-RAM store)."""

    def __init__(self, port: int = 0):
        if _lib is None:
            raise RuntimeError(
                "libkvtransfer.so not built — run `make kvtransfer`"
            )
        self._handle = _lib.kvt_server_start(port)
        if not self._handle:
            raise OSError(f"failed to start block transfer server on port {port}")

    @property
    def port(self) -> int:
        return _lib.kvt_server_port(self._handle)

    def put(self, block_hash: int, data: bytes) -> None:
        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
        if _lib.kvt_server_put(self._handle, block_hash & (2**64 - 1), buf, len(data)):
            raise OSError("kvt_server_put failed")

    def remove(self, block_hash: int) -> bool:
        return _lib.kvt_server_remove(self._handle, block_hash & (2**64 - 1)) == 0

    def corrupt(self, block_hash: int) -> bool:
        """Fault-injection hook: flip a byte of the stored block WITHOUT
        touching its put-time checksum (the silent bit-flip the end-to-end
        integrity check exists to catch). False when the block is absent,
        empty, or the loaded .so predates the integrity ABI."""
        if not integrity_api_available():
            return False
        return _lib.kvt_server_corrupt(
            self._handle, block_hash & (2**64 - 1)
        ) == 0

    def block_count(self) -> int:
        return _lib.kvt_server_block_count(self._handle)

    def close(self) -> None:
        if self._handle:
            _lib.kvt_server_stop(self._handle)
            self._handle = None

    def __del__(self):  # noqa: D105
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass


# -- pooled keep-alive DCN client ---------------------------------------------


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


@dataclass
class TransferClientConfig:
    connect_timeout_ms: int = 2000
    io_timeout_ms: int = 5000
    # Reconnect-and-retry attempts after a transport error/timeout (the
    # request is idempotent — a fetch has no side effects — so a retry can
    # never double-apply anything).
    retries: int = 1
    # Blocks per wire request; longer chains split into multiple round
    # trips (still 1/max_batch of the serial count).
    max_batch: int = 256
    # End-to-end integrity: fetch over the v2 checksummed wire when the
    # loaded .so carries it; a failed per-block check degrades to a miss
    # (counted), never a landed corrupt block. False restores the v1 wire
    # byte-for-byte (mixed-version peers).
    verify_integrity: bool = True
    # Per-peer circuit breaker: `breaker_failure_threshold` consecutive
    # failed results (timeouts, transport errors, corruption) open the
    # peer's breaker; while open every fetch is skipped instantly (a
    # counted miss — no timeout paid). After `breaker_cooldown_s` the
    # breaker goes half-open and admits ONE probe fetch: success closes
    # it, failure re-opens with a fresh cooldown. Threshold <= 0 disables.
    breaker_failure_threshold: int = 5
    breaker_cooldown_s: float = 30.0
    # Hedged fetches (fetch_many_hedged): when a chain run has >= 2
    # holders, a hedge to the next holder launches after an adaptive
    # delay tracking the primary peer's latency tail (EWMA mean + 4x EWMA
    # deviation — a p99 proxy), clamped to [floor, cap].
    hedge_delay_floor_s: float = 0.005
    hedge_delay_cap_s: float = 2.0
    # Idle-TTL on per-peer state: pooled keep-alive connections and
    # peer failure-memory rows untouched for this long are closed/
    # dropped by `sweep_idle` (ridden by `status()` — no threads).
    # A peer whose breaker is NOT closed is never dropped: an open
    # breaker on a live peer is active protection, and it re-closes
    # through its own half-open probe, not through forgetting. 0
    # disables the sweep (the seed behavior).
    peer_idle_ttl_s: float = 0.0

    @classmethod
    def from_env(cls) -> "TransferClientConfig":
        """Env-tunable form for the process-wide default client (the knobs
        a deployment flips without code: see docs/configuration.md)."""
        return cls(
            connect_timeout_ms=_env_int("KVTPU_TRANSFER_CONNECT_TIMEOUT_MS", 2000),
            io_timeout_ms=_env_int("KVTPU_TRANSFER_IO_TIMEOUT_MS", 5000),
            retries=_env_int("KVTPU_TRANSFER_RETRIES", 1),
            verify_integrity=_env_int("KVTPU_TRANSFER_VERIFY_INTEGRITY", 1) != 0,
            breaker_failure_threshold=_env_int(
                "KVTPU_TRANSFER_BREAKER_THRESHOLD", 5
            ),
            breaker_cooldown_s=_env_float(
                "KVTPU_TRANSFER_BREAKER_COOLDOWN_S", 30.0
            ),
            hedge_delay_floor_s=_env_float(
                "KVTPU_TRANSFER_HEDGE_FLOOR_MS", 5.0
            ) / 1e3,
            hedge_delay_cap_s=_env_float(
                "KVTPU_TRANSFER_HEDGE_CAP_MS", 2000.0
            ) / 1e3,
            peer_idle_ttl_s=_env_float(
                "KVTPU_TRANSFER_PEER_IDLE_TTL_S", 0.0
            ),
        )


# Breaker states — the fixed vocabulary the transition metric's `state`
# label carries (pinned in tests/test_metrics_hygiene.py).
BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"
BREAKER_STATES = (BREAKER_CLOSED, BREAKER_OPEN, BREAKER_HALF_OPEN)

# Per-block error kinds — the fixed vocabulary of
# kvcache_transfer_block_errors_total{kind} (pinned in the hygiene walk).
# `transport`: the whole round trip failed its bounded timeout/retry
# budget; `oversized`: the peer answered with a block over the caller's
# cap (drained, dropped); `corrupt`: the end-to-end checksum failed on
# receipt; `breaker_open`: skipped instantly because the peer's breaker
# was open.
TRANSFER_ERROR_KINDS = ("transport", "oversized", "corrupt", "breaker_open")

# Sentinels for per-block wire statuses inside _transport_fetch results.
_OVERSIZED = object()  # -3: present remotely but over the caller's cap
_CORRUPT = object()    # -4: failed the end-to-end checksum on receipt


class PeerBreaker:
    """Per-peer circuit breaker: closed -> open on consecutive failures,
    half-open single-probe recovery. Clock-driven (the owner passes `now`
    into every call), so transitions are deterministic under test and
    under the simulated fleet clock."""

    def __init__(self, failure_threshold: int, cooldown_s: float):
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        self.state = BREAKER_CLOSED
        self.consecutive_failures = 0
        self.opened_at: Optional[float] = None
        self.opens = 0
        self._probe_inflight = False
        self._mu = threading.Lock()

    @property
    def enabled(self) -> bool:
        return self.failure_threshold > 0

    def allow(self, now: float):
        """(allowed, transition): whether a fetch may proceed now, plus the
        (old, new) state transition this call performed (open -> half_open
        when the cooldown elapsed), if any."""
        if not self.enabled:
            return True, None
        with self._mu:
            if self.state == BREAKER_CLOSED:
                return True, None
            if self.state == BREAKER_OPEN:
                if now - (self.opened_at or 0.0) < self.cooldown_s:
                    return False, None
                # Cooldown over: half-open, this caller becomes the probe.
                self.state = BREAKER_HALF_OPEN
                self._probe_inflight = True
                return True, (BREAKER_OPEN, BREAKER_HALF_OPEN)
            # half-open: exactly one probe at a time.
            if self._probe_inflight:
                return False, None
            self._probe_inflight = True
            return True, None

    def record_success(self, now: float):
        """Returns the (old, new) transition, if any."""
        with self._mu:
            self.consecutive_failures = 0
            self._probe_inflight = False
            if self.state == BREAKER_CLOSED:
                return None
            old, self.state = self.state, BREAKER_CLOSED
            self.opened_at = None
            return (old, BREAKER_CLOSED)

    def record_failure(self, now: float):
        """Returns the (old, new) transition, if any."""
        if not self.enabled:
            return None
        with self._mu:
            self.consecutive_failures += 1
            self._probe_inflight = False
            if self.state == BREAKER_HALF_OPEN:
                # Failed probe: straight back to open, fresh cooldown.
                self.state = BREAKER_OPEN
                self.opened_at = now
                self.opens += 1
                return (BREAKER_HALF_OPEN, BREAKER_OPEN)
            if (
                self.state == BREAKER_CLOSED
                and self.consecutive_failures >= self.failure_threshold
            ):
                self.state = BREAKER_OPEN
                self.opened_at = now
                self.opens += 1
                return (BREAKER_CLOSED, BREAKER_OPEN)
            return None

    def status(self, now: Optional[float] = None) -> dict:
        with self._mu:
            out = {
                "state": self.state,
                "consecutive_failures": self.consecutive_failures,
                "opens": self.opens,
            }
            if self.state == BREAKER_OPEN and now is not None:
                out["cooldown_remaining_s"] = round(
                    max(
                        self.cooldown_s - (now - (self.opened_at or 0.0)), 0.0
                    ),
                    3,
                )
            return out


class _PeerState:
    """Per-(host, port) client-side failure memory: the breaker plus an
    EWMA latency profile (mean + mean-absolute-deviation — the hedge
    delay's p99 proxy) and per-peer counters."""

    __slots__ = (
        "key", "breaker", "lock", "lat_ewma", "lat_dev", "lat_n",
        "fetches", "failures", "corrupt_blocks", "breaker_skips",
        "last_used",
    )

    _ALPHA = 0.2  # EWMA smoothing for the latency profile

    def __init__(self, key: str, config: TransferClientConfig):
        self.key = key
        self.breaker = PeerBreaker(
            config.breaker_failure_threshold, config.breaker_cooldown_s
        )
        self.lock = threading.Lock()
        self.lat_ewma = 0.0
        self.lat_dev = 0.0
        self.lat_n = 0
        self.fetches = 0
        self.failures = 0
        self.corrupt_blocks = 0
        self.breaker_skips = 0
        self.last_used = 0.0

    def note_latency(self, seconds: float) -> None:
        with self.lock:
            if self.lat_n == 0:
                self.lat_ewma = seconds
                self.lat_dev = 0.0
            else:
                err = seconds - self.lat_ewma
                self.lat_ewma += self._ALPHA * err
                self.lat_dev += self._ALPHA * (abs(err) - self.lat_dev)
            self.lat_n += 1

    def status(self, now: Optional[float] = None) -> dict:
        with self.lock:
            out = {
                "fetches": self.fetches,
                "failures": self.failures,
                "corrupt_blocks": self.corrupt_blocks,
                "breaker_skips": self.breaker_skips,
                "ewma_fetch_latency_ms": round(self.lat_ewma * 1e3, 3),
                "ewma_latency_dev_ms": round(self.lat_dev * 1e3, 3),
                "latency_samples": self.lat_n,
            }
        out.update(self.breaker.status(now))
        return out


class _Conn:
    __slots__ = ("fd", "lock", "last_used")

    def __init__(self):
        self.fd = -1
        self.lock = threading.Lock()
        self.last_used = 0.0


class TransferClient:
    """Pooled keep-alive fetch client for the DCN leg.

    One persistent connection per (host, port); `fetch_many` moves a whole
    chain in one round trip through the C++ multi-block protocol. Every
    operation is bounded by connect/read timeouts and a bounded retry —
    on exhaustion the blocks come back as None (a miss the tiering layer
    already handles) and `transfer_failures` counts the event, so a dead
    peer can never wedge the serving thread on a stuck socket.

    Chaos hardening on top of the pooled protocol:

    - **End-to-end integrity**: fetches ride the v2 checksummed wire
      (put-time FNV-1a 64 per block, verified GIL-free on receipt); a
      failed check degrades the block to a miss — counted in
      `kvcache_transfer_corrupt_blocks_total` — and is NEVER landed.
    - **Per-peer circuit breakers**: consecutive failures (timeouts,
      transport errors, corruption) open the peer's breaker; open peers
      are skipped instantly instead of paying the full timeout, with
      half-open single-probe recovery. Transitions are observable
      (`on_breaker_transition` callback + the transitions metric).
    - **Hedged fetches** (`fetch_many_hedged`): given several holders of
      a chain run, a hedge launches to the next holder after an adaptive
      per-peer-latency delay; the first valid reply wins and the loser's
      reply is drained and discarded (a fetch is idempotent — nothing can
      double-land).

    The clock is injectable (breaker windows + latency profile), so every
    transition is deterministic under test and the fleet-sim clock.
    """

    def __init__(
        self,
        config: Optional[TransferClientConfig] = None,
        clock: Callable[[], float] = time.monotonic,
        on_breaker_transition: Optional[Callable[[str, str, str], None]] = None,
        on_fetch_misses: Optional[
            Callable[[str, int, List[int], List[int]], None]
        ] = None,
    ):
        self.config = config or TransferClientConfig()
        self.clock = clock
        # Called as (peer_key, old_state, new_state) on every breaker
        # transition — the FleetHealthTracker feed.
        self.on_breaker_transition = on_breaker_transition
        # Called as (host, port, requested_hashes, missing_hashes) when a
        # SUCCESSFUL round trip came back with per-block "missing"
        # answers (-2 on the wire: the peer is healthy and explicitly
        # disclaims the blocks). This is ground truth against whatever
        # advertised the peer as a holder — the anti-entropy fetch-miss
        # feedback seam (antientropy/feedback.py). Transport failures,
        # corruption, and breaker skips never fire it: those say nothing
        # about what the peer holds.
        self.on_fetch_misses = on_fetch_misses
        self._pool: Dict[Tuple[str, int], _Conn] = {}
        self._peers: Dict[Tuple[str, int], _PeerState] = {}
        self._mu = threading.Lock()  # pool/peer maps only
        self.stats: Dict[str, int] = {
            "connects": 0, "reconnects": 0, "failures": 0,
            "batch_fetches": 0, "blocks_fetched": 0,
            "corrupt_blocks": 0, "oversized_blocks": 0,
            "breaker_skipped_blocks": 0, "hedges": 0, "hedge_wins": 0,
            "missing_blocks": 0, "idle_closed_conns": 0,
            "idle_dropped_peers": 0, "reaped_peers": 0,
        }

    def _conn(self, host: str, port: int) -> _Conn:
        with self._mu:
            conn = self._pool.get((host, port))
            if conn is None:
                conn = self._pool[(host, port)] = _Conn()
            conn.last_used = self.clock()
            return conn

    def peer_state(self, host: str, port: int) -> _PeerState:
        with self._mu:
            peer = self._peers.get((host, port))
            if peer is None:
                peer = self._peers[(host, port)] = _PeerState(
                    f"{host}:{port}", self.config
                )
            peer.last_used = self.clock()
            return peer

    def sweep_idle(self, now: Optional[float] = None) -> int:
        """Close pooled connections and drop peer failure-memory rows
        untouched for `peer_idle_ttl_s` (0 disables). Lazy and clock-
        driven — `status()` rides it, the resource governor's reap plane
        may call it on its own cadence. Peer rows whose breaker is not
        CLOSED survive any idle age: an open breaker is live protection
        for the next fetch, and dropping it would reset the peer to
        trusted mid-outage. Returns rows removed (conns + peers)."""
        ttl = self.config.peer_idle_ttl_s
        if ttl <= 0:
            return 0
        if now is None:
            now = self.clock()
        removed = 0
        to_close: List[_Conn] = []
        with self._mu:
            for addr in [
                a for a, c in self._pool.items()
                if now - c.last_used >= ttl
            ]:
                to_close.append(self._pool.pop(addr))
            for addr in [
                a for a, p in self._peers.items()
                if now - p.last_used >= ttl
                and p.breaker.state == BREAKER_CLOSED
            ]:
                del self._peers[addr]
                self.stats["idle_dropped_peers"] += 1
                removed += 1
        for conn in to_close:
            with conn.lock:
                self._drop(conn)
            self.stats["idle_closed_conns"] += 1
            removed += 1
        return removed

    def forget_host(self, host: str) -> int:
        """Departure reap hook: drop every pooled connection and peer row
        addressed to `host`, whatever its port and breaker state — the
        pod behind the address left the fleet, so its failure memory
        protects nothing and its sockets lead nowhere. Returns rows
        removed."""
        removed = 0
        to_close: List[_Conn] = []
        with self._mu:
            for addr in [a for a in self._pool if a[0] == host]:
                to_close.append(self._pool.pop(addr))
            for addr in [a for a in self._peers if a[0] == host]:
                del self._peers[addr]
                self.stats["reaped_peers"] += 1
                removed += 1
        for conn in to_close:
            with conn.lock:
                self._drop(conn)
            removed += 1
        return removed

    def entries(self) -> int:
        """Per-peer rows + pooled connections — the resource accountant's
        O(1) meter read."""
        with self._mu:
            return len(self._peers) + len(self._pool)

    def _ensure_connected(self, conn: _Conn, host: str, port: int) -> bool:
        if conn.fd >= 0:
            return True
        conn.fd = _lib.kvt_connect(
            host.encode(), port, self.config.connect_timeout_ms
        )
        if conn.fd >= 0:
            self.stats["connects"] += 1
            return True
        return False

    def _drop(self, conn: _Conn) -> None:
        if conn.fd >= 0:
            _lib.kvt_close(conn.fd)
            conn.fd = -1

    def _fail(self, host: str, port: int, n: int, what: str) -> None:
        self.stats["failures"] += 1
        metrics.count_transfer_failure()
        logger.warning(
            "transfer %s from %s:%d failed after %d attempt(s) (%d block(s) "
            "treated as missing)", what, host, port,
            self.config.retries + 1, n,
        )

    def _has_client_api(self) -> bool:
        """Seam for tests/fakes: a subclass that overrides
        `_transport_fetch` with scripted outcomes returns True here so the
        breaker/hedge/integrity logic runs without the native lib."""
        return client_api_available()

    # -- per-peer bookkeeping seam ----------------------------------------

    def _note_transition(self, peer: _PeerState, transition) -> None:
        if transition is None:
            return
        old, new = transition
        metrics.count_breaker_transition(new)
        log = logger.info if new == BREAKER_CLOSED else logger.warning
        log("transfer breaker for %s: %s -> %s", peer.key, old, new)
        if self.on_breaker_transition is not None:
            try:
                self.on_breaker_transition(peer.key, old, new)
            except Exception as e:  # noqa: BLE001 - observer must not
                logger.debug("breaker transition callback failed: %s", e)

    def allow_peer(self, host: str, port: int) -> bool:
        """Breaker gate: False means the peer must be skipped right now
        (its breaker is open, or half-open with the probe slot taken)."""
        peer = self.peer_state(host, port)
        allowed, transition = peer.breaker.allow(self.clock())
        self._note_transition(peer, transition)
        return allowed

    def note_result(
        self,
        host: str,
        port: int,
        ok: bool,
        latency_s: float,
        corrupt_blocks: int = 0,
        blocks: int = 1,
    ) -> None:
        """Record one fetch outcome against the peer's failure memory:
        latency EWMA (successes only — a timeout is not a latency sample),
        corruption counters, and the breaker (corruption counts as a
        failure: a peer shipping garbage is as untrustworthy as a dead
        one). Public because the chaos fault injector
        (kv_connectors/faults.py) stands in for the wire and reports the
        outcomes it synthesizes through the SAME seam."""
        peer = self.peer_state(host, port)
        now = self.clock()
        if ok:
            peer.note_latency(latency_s)
            with peer.lock:
                peer.fetches += 1
        else:
            with peer.lock:
                peer.failures += 1
            metrics.count_transfer_block_error("transport", blocks)
        if corrupt_blocks:
            with peer.lock:
                peer.corrupt_blocks += corrupt_blocks
            self.stats["corrupt_blocks"] += corrupt_blocks
            metrics.count_transfer_corrupt(corrupt_blocks)
            metrics.count_transfer_block_error("corrupt", corrupt_blocks)
            logger.warning(
                "%d corrupt block(s) detected from %s:%d — discarded "
                "(checksum mismatch), falling back", corrupt_blocks, host,
                port,
            )
        if ok and not corrupt_blocks:
            self._note_transition(peer, peer.breaker.record_success(now))
        else:
            self._note_transition(peer, peer.breaker.record_failure(now))

    def _breaker_skip(self, host: str, port: int, n: int) -> List[None]:
        peer = self.peer_state(host, port)
        with peer.lock:
            peer.breaker_skips += 1
        self.stats["breaker_skipped_blocks"] += n
        metrics.count_transfer_block_error("breaker_open", n)
        return [None] * n

    # -- fetch paths -------------------------------------------------------

    def fetch_one(
        self, host: str, port: int, block_hash: int, max_size: int,
    ) -> Optional[bytes]:
        """One block over the pooled connection. None when missing remotely
        OR when every attempt failed (counted in `transfer_failures`).
        Rides the same breaker-gated, integrity-checked path as
        `fetch_many` (an n=1 multi-block round trip)."""
        if not self._has_client_api():
            return _legacy_fetch(host, port, block_hash, max_size)
        return self.fetch_many(host, port, [block_hash], max_size)[0]

    def fetch_many(
        self, host: str, port: int, block_hashes: List[int], max_size: int,
    ) -> List[Optional[bytes]]:
        """Fetch a chain in one round trip per `max_batch` blocks. Returns
        payloads aligned with `block_hashes`; None marks a block missing
        remotely, failed-integrity (detected corrupt), skipped behind an
        open breaker, or lost to a (bounded, retried, counted) transport
        failure."""
        if not block_hashes:
            return []
        if not self._has_client_api():
            return [
                _legacy_fetch(host, port, h, max_size) for h in block_hashes
            ]
        if not self.allow_peer(host, port):
            return self._breaker_skip(host, port, len(block_hashes))
        out: List[Optional[bytes]] = []
        mb = max(1, self.config.max_batch)
        for i in range(0, len(block_hashes), mb):
            out.extend(
                self._fetch_chunk(host, port, block_hashes[i:i + mb], max_size)
            )
        return out

    def _transport_fetch(self, host, port, hashes, max_size):
        """The lib-touching leg of one chunk: (ok, entries). `entries` is
        aligned with `hashes`: payload bytes, None (missing remotely), or
        the _OVERSIZED/_CORRUPT sentinels. ok=False means the whole round
        trip failed its bounded retry budget (entries is None). Overridden
        by tests and the chaos fault injector."""
        n = len(hashes)
        cap = max(max_size, 1)
        arr = (ctypes.c_uint64 * n)(*[h & (2**64 - 1) for h in hashes])
        buf = (ctypes.c_uint8 * (n * cap))()
        lens = (ctypes.c_int64 * n)()
        use_v2 = self.config.verify_integrity and integrity_api_available()
        fetch_fn = _lib.kvt_fetch_many2 if use_v2 else _lib.kvt_fetch_many
        conn = self._conn(host, port)
        with conn.lock:
            for attempt in range(self.config.retries + 1):
                if attempt:
                    self.stats["reconnects"] += 1
                if not self._ensure_connected(conn, host, port):
                    continue
                rc = fetch_fn(
                    conn.fd, n, arr, buf, cap, lens, self.config.io_timeout_ms
                )
                if rc == 0:
                    base = ctypes.addressof(buf)
                    entries = []
                    for i in range(n):
                        ln = lens[i]
                        if ln >= 0:
                            entries.append(ctypes.string_at(base + i * cap, ln))
                        elif ln == -3:
                            entries.append(_OVERSIZED)
                        elif ln == -4:
                            entries.append(_CORRUPT)
                        else:
                            entries.append(None)
                    return True, entries
                self._drop(conn)  # transport error: reconnect and retry
        return False, None

    def _fetch_chunk(
        self, host: str, port: int, hashes: List[int], max_size: int,
    ) -> List[Optional[bytes]]:
        n = len(hashes)
        # Peer identity rides the trace META (data), never a metric label.
        obs.annotate("peer", f"{host}:{port}")
        with obs.stage("transfer.dcn_fetch"):
            t0 = self.clock()
            ok, entries = self._transport_fetch(host, port, hashes, max_size)
            latency = max(self.clock() - t0, 0.0)
        if not ok:
            self.note_result(host, port, ok=False, latency_s=latency, blocks=n)
            self._fail(host, port, n, "batch fetch")
            return [None] * n
        corrupt = 0
        missing: List[int] = []
        result: List[Optional[bytes]] = []
        for h, entry in zip(hashes, entries):
            if entry is _CORRUPT:
                corrupt += 1
                result.append(None)  # detected — treated exactly like a miss
            elif entry is _OVERSIZED:
                self.stats["oversized_blocks"] += 1
                metrics.count_transfer_block_error("oversized", 1)
                logger.warning(
                    "block %x from %s:%d exceeds cap %d — dropped",
                    h, host, port, max(max_size, 1),
                )
                result.append(None)
            else:
                if entry is None:
                    # Explicit per-block miss on a healthy round trip:
                    # the peer disclaims the block (-2). The one wire
                    # status that is EVIDENCE rather than damage — fed to
                    # the anti-entropy seam below.
                    missing.append(h)
                result.append(entry)
        self.stats["batch_fetches"] += 1
        self.stats["blocks_fetched"] += n
        self.note_result(
            host, port, ok=True, latency_s=latency,
            corrupt_blocks=corrupt, blocks=n,
        )
        if missing:
            self.stats["missing_blocks"] += len(missing)
            if self.on_fetch_misses is not None:
                try:
                    self.on_fetch_misses(host, port, list(hashes), missing)
                except Exception as e:  # noqa: BLE001 - observer must not
                    logger.debug("fetch-miss callback failed: %s", e)
        return result

    # -- hedged fetches ----------------------------------------------------

    def hedge_delay_s(self, host: str, port: int) -> float:
        """Adaptive hedge trigger for a peer: EWMA latency mean + 4x EWMA
        mean-absolute-deviation (a p99 proxy that needs no sample ring),
        clamped to [hedge_delay_floor_s, hedge_delay_cap_s]."""
        peer = self.peer_state(host, port)
        with peer.lock:
            if peer.lat_n == 0:
                est = self.config.hedge_delay_floor_s
            else:
                est = peer.lat_ewma + 4.0 * peer.lat_dev
        return min(
            max(est, self.config.hedge_delay_floor_s),
            self.config.hedge_delay_cap_s,
        )

    def fetch_many_hedged(
        self,
        addrs: List[Tuple[str, int]],
        block_hashes: List[int],
        max_size: int,
    ) -> List[Optional[bytes]]:
        """Fetch a chain run that has several holders. The first holder is
        the primary; if it has not answered within the adaptive hedge
        delay — or answered with holes (transport failure, corruption,
        open breaker) — a hedge launches to the next holder. The first
        COMPLETE reply (every block present) wins; a losing fetch still
        runs to completion on its own pooled connection (the reply is
        drained, keeping the connection usable) and its payloads are
        discarded, so a block can never be returned twice. With no
        complete reply anywhere, the reply covering the most blocks wins
        (primary on ties) — the caller's chain-cut logic handles the
        holes."""
        if not block_hashes:
            return []
        if not addrs:
            return [None] * len(block_hashes)
        primary, backups = addrs[0], list(addrs[1:])
        if not backups:
            return self.fetch_many(
                primary[0], primary[1], block_hashes, max_size
            )

        cv = threading.Condition()
        replies: List[tuple] = []  # (addr, result), completion order
        inflight = [0]

        def run(addr):
            result = self.fetch_many(
                addr[0], addr[1], list(block_hashes), max_size
            )
            with cv:
                replies.append((addr, result))
                inflight[0] -= 1
                cv.notify_all()

        def launch(addr):
            inflight[0] += 1
            threading.Thread(
                target=run, args=(addr,), name="kv-hedge-fetch", daemon=True
            ).start()

        def complete(result):
            return all(payload is not None for payload in result)

        with cv:
            launch(primary)
            examined = 0
            cv.wait_for(
                lambda: len(replies) > 0,
                timeout=self.hedge_delay_s(*primary),
            )
            backup_iter = iter(backups)
            while True:
                while examined < len(replies):
                    addr, result = replies[examined]
                    examined += 1
                    if complete(result):
                        if addr != primary:
                            self.stats["hedge_wins"] += 1
                        return result
                nxt = next(backup_iter, None)
                if nxt is not None:
                    # Primary (or an earlier hedge) is slow or answered
                    # with holes: fan to the next rendezvous-ranked holder.
                    launch(nxt)
                    self.stats["hedges"] += 1
                    metrics.count_transfer_hedge()
                elif inflight[0] == 0:
                    break
                done = examined  # rebind for the closure below
                cv.wait_for(
                    lambda: len(replies) > done or inflight[0] == 0
                )
            # No complete reply: most-covered wins, primary on ties
            # (replies is completion-ordered, primary launched first).
            best: Optional[List[Optional[bytes]]] = None
            best_cover = -1
            for addr, result in replies:
                cover = sum(payload is not None for payload in result)
                if cover > best_cover:
                    best, best_cover = result, cover
            return best if best is not None else [None] * len(block_hashes)

    def register_knobs(self, registry) -> None:
        """Publish the hedge delay floor to the autopilot
        (autopilot/knobs.py). The per-peer hedge delay is EWMA-derived
        and clamped to [floor, cap] on every fetch, so lowering the
        floor is the config surface that launches hedges earlier when
        breakers are tripping. Bounds: [1ms, cap] — a hedge can never
        fire before the wire could plausibly answer, and the controller
        can never push the floor past the operator's cap."""
        from llm_d_kv_cache_manager_tpu.autopilot.knobs import (
            KNOB_TRANSFER_HEDGE_FLOOR,
            KnobSpec,
        )

        cfg = self.config
        registry.register(
            KnobSpec(
                name=KNOB_TRANSFER_HEDGE_FLOOR,
                floor=min(0.001, cfg.hedge_delay_floor_s),
                ceiling=cfg.hedge_delay_cap_s,
                max_step=max(cfg.hedge_delay_floor_s / 2.0, 0.001),
                description="minimum delay before a hedged fetch launches",
            ),
            get=lambda: cfg.hedge_delay_floor_s,
            set_=lambda v: setattr(cfg, "hedge_delay_floor_s", float(v)),
        )

    # -- introspection -----------------------------------------------------

    def status(self) -> dict:
        """Transfer-plane health snapshot (the /readyz `transfer` section):
        aggregate counters plus per-peer breaker state, consecutive
        failures, and the EWMA fetch-latency profile."""
        now = self.clock()
        self.sweep_idle(now)
        with self._mu:
            peers = dict(self._peers)
            pooled = len(self._pool)
        return {
            "stats": dict(self.stats),
            "breaker": {
                "failure_threshold": self.config.breaker_failure_threshold,
                "cooldown_s": self.config.breaker_cooldown_s,
            },
            "pooled_connections": pooled,
            "peer_idle_ttl_s": self.config.peer_idle_ttl_s,
            "verify_integrity": (
                self.config.verify_integrity and integrity_api_available()
            ),
            "peers": {
                peer.key: peer.status(now) for peer in peers.values()
            },
        }

    def close(self) -> None:
        with self._mu:
            conns = list(self._pool.values())
            self._pool.clear()
        for conn in conns:
            with conn.lock:
                self._drop(conn)


_default_client: Optional[TransferClient] = None
_default_client_mu = threading.Lock()


def default_client() -> TransferClient:
    """Process-wide pooled client (module-level fetch_block/fetch_blocks).
    Env-tunable: KVTPU_TRANSFER_{CONNECT_TIMEOUT_MS, IO_TIMEOUT_MS,
    RETRIES, VERIFY_INTEGRITY, BREAKER_THRESHOLD, BREAKER_COOLDOWN_S,
    HEDGE_FLOOR_MS, HEDGE_CAP_MS}."""
    global _default_client
    with _default_client_mu:
        if _default_client is None:
            _default_client = TransferClient(TransferClientConfig.from_env())
        return _default_client


def peek_default_client() -> Optional[TransferClient]:
    """The process-wide client if one exists — WITHOUT creating it (the
    /readyz transfer section must not conjure a transfer plane into a
    process that never used one)."""
    with _default_client_mu:
        return _default_client


def _legacy_fetch(
    host: str, port: int, block_hash: int, max_size: int,
) -> Optional[bytes]:
    """Throwaway-connection fetch via the old ABI (stale .so builds). No
    timeout bound — exactly the seed behavior this PR replaces."""
    buf = (ctypes.c_uint8 * max(max_size, 1))()
    n = _lib.kvt_fetch(host.encode(), port, block_hash & (2**64 - 1), buf, max_size)
    if n == -2:
        return None
    if n < 0:
        metrics.count_transfer_failure()
        logger.warning("legacy fetch from %s:%d failed", host, port)
        return None
    return ctypes.string_at(buf, n)


def fetch_block(host: str, port: int, block_hash: int, max_size: int) -> Optional[bytes]:
    """Fetch a staged block from a pod over the pooled keep-alive client.
    None if the block is missing (a present-but-empty block returns b"") OR
    if the transfer failed after the bounded timeout/retry budget — the
    failure is logged and counted (`transfer_failures`), never raised, so a
    dead peer degrades to a cache miss instead of an unbounded hang."""
    if _lib is None:
        raise RuntimeError("libkvtransfer.so not built")
    return default_client().fetch_one(host, port, block_hash, max_size)


def fetch_blocks(
    host: str, port: int, block_hashes: List[int], max_size: int,
) -> List[Optional[bytes]]:
    """Batched `fetch_block`: one round trip per chain (multi-block wire
    protocol). Same None semantics per block."""
    if _lib is None:
        raise RuntimeError("libkvtransfer.so not built")
    return default_client().fetch_many(host, port, block_hashes, max_size)


@dataclass
class KVConnectorConfig:
    port: int = 0  # 0 -> ephemeral
    device_tier_hbm: str = "hbm"
    device_tier_host: str = "host"
    # Completion-queue bound for offload_async: at most this many dispatched
    # D2H snapshots awaiting drain (each holds its device buffers alive);
    # dispatching past the bound drains the oldest entry first.
    max_inflight_offloads: int = 16
    # DCN client bounds (threaded into this connector's TransferClient).
    connect_timeout_ms: int = 2000
    fetch_timeout_ms: int = 5000
    fetch_retries: int = 1
    fetch_batch_size: int = 256
    # Chaos hardening (threaded into the TransferClient; see
    # TransferClientConfig for semantics).
    verify_integrity: bool = True
    breaker_failure_threshold: int = 5
    breaker_cooldown_s: float = 30.0


class KVConnector:
    """Per-pod connector: moves KV pages between HBM, host staging, and
    remote pods, emitting the control-plane events for each move."""

    def __init__(
        self,
        config: KVConnectorConfig | None = None,
        event_sink: Optional[Callable[[EventBatch], None]] = None,
    ):
        self.config = config or KVConnectorConfig()
        self.server = BlockTransferServer(self.config.port)
        self.event_sink = event_sink
        self.client = TransferClient(TransferClientConfig(
            connect_timeout_ms=self.config.connect_timeout_ms,
            io_timeout_ms=self.config.fetch_timeout_ms,
            retries=self.config.fetch_retries,
            max_batch=self.config.fetch_batch_size,
            verify_integrity=self.config.verify_integrity,
            breaker_failure_threshold=self.config.breaker_failure_threshold,
            breaker_cooldown_s=self.config.breaker_cooldown_s,
        ))
        # Dispatched-but-undrained offload snapshots, FIFO. Entries hold
        # the device arrays whose copy_to_host_async is in flight.
        self._offloads: deque = deque()
        self._offload_mu = threading.Lock()

    @property
    def port(self) -> int:
        return self.server.port

    # -- HBM <-> host staging -------------------------------------------------

    def offload(
        self, block_hash: int, k_page, v_page, token_ids, block_size: int,
        parent_hash: Optional[int] = None,
    ) -> None:
        """Stage one page pair out of HBM into the host store (+ event).
        Synchronous form: dispatches the D2H copy and drains the whole
        completion queue (older async offloads included) before returning."""
        self.offload_async(block_hash, k_page, v_page, token_ids, block_size,
                           parent_hash)
        self.drain_offloads()

    def offload_async(
        self, block_hash: int, k_page, v_page, token_ids, block_size: int,
        parent_hash: Optional[int] = None, lora_id: Optional[int] = None,
    ) -> None:
        """Dispatch a page pair's D2H copy NOW and return: the DMA overlaps
        whatever compute is queued behind it, and the block is staged (+
        host-tier event) when `drain_offloads` resolves the completion
        queue. The snapshot is content-stable — the copy consumes the pages
        in enqueue order, so later device writes cannot corrupt it. Past
        `max_inflight_offloads`, the oldest entry is drained first (bounded
        memory, still pipelined)."""
        with obs.stage("transfer.offload_dispatch"):
            for page in (k_page, v_page):
                try:
                    # On the CPU backend there is no DMA engine to overlap:
                    # copy_to_host_async degenerates to a synchronous
                    # memcpy, which would move the whole copy ONTO the
                    # dispatch path — the opposite of the point. Skip the
                    # hint there; the drain's device_get pays the same
                    # memcpy off the critical path instead.
                    if next(iter(page.devices())).platform != "cpu":
                        page.copy_to_host_async()
                except Exception:  # noqa: BLE001 - hint; device_get works
                    pass
            entry = (block_hash, k_page, v_page, list(token_ids), block_size,
                     parent_hash, lora_id)
            drain_oldest = []
            with self._offload_mu:
                self._offloads.append(entry)
                while len(self._offloads) > max(
                    1, self.config.max_inflight_offloads
                ):
                    drain_oldest.append(self._offloads.popleft())
        for old in drain_oldest:
            self._resolve_offload(old)

    def drain_offloads(self, max_blocks: Optional[int] = None) -> List[int]:
        """Resolve pending offload snapshots (oldest first): wait out the
        residual D2H sync, stage the bytes, emit the host-tier event.
        Returns the staged block hashes in dispatch order."""
        done: List[int] = []
        while max_blocks is None or len(done) < max_blocks:
            with self._offload_mu:
                if not self._offloads:
                    break
                entry = self._offloads.popleft()
            self._resolve_offload(entry)
            done.append(entry[0])
        return done

    @property
    def pending_offloads(self) -> int:
        with self._offload_mu:
            return len(self._offloads)

    def _resolve_offload(self, entry) -> None:
        import jax

        with obs.stage("transfer.offload_drain"):
            block_hash, k_page, v_page, token_ids, block_size, parent, lora = entry
            k_np = np.asarray(jax.device_get(k_page))
            v_np = np.asarray(jax.device_get(v_page))
            self.stage(block_hash, k_np.tobytes() + v_np.tobytes(), token_ids,
                       block_size, parent, lora)

    def restore(self, block_hash: int, like_k, like_v) -> Optional[Tuple]:
        """Bring a host-staged block back as (k_page, v_page) arrays shaped
        like the given templates."""
        payload = self.fetch_staged(block_hash, like_k.nbytes + like_v.nbytes)
        return self._decode(payload, like_k, like_v)

    def drop(self, block_hash: int) -> None:
        if self.server.remove(block_hash):
            self._emit(EventBatch(ts=0.0, events=[
                BlockRemoved(block_hashes=[block_hash],
                             medium=self.config.device_tier_host)
            ]))

    # -- opaque-payload tier API (engine/tiering.py drives these) -------------

    def stage(
        self, block_hash: int, payload: bytes, token_ids, block_size: int,
        parent_hash: Optional[int] = None, lora_id: Optional[int] = None,
    ) -> None:
        """Stage an already-serialized block in the host store (+ host-tier
        BlockStored). The payload layout is the engine's business — the data
        plane treats blocks as opaque bytes named by their hash."""
        self.server.put(block_hash, payload)
        self._emit_stored(block_hash, token_ids, block_size, parent_hash,
                          self.config.device_tier_host, lora_id)

    def onboard_payload(
        self, host: str, port: int, block_hash: int, max_size: int,
    ) -> Optional[bytes]:
        """Pull a block's bytes from a pod's transfer server; None if absent
        or the transfer failed its bounded retry. The caller lands it in
        HBM and the block manager emits the device-tier BlockStored, so no
        event fires here."""
        return self.client.fetch_one(host, port, block_hash, max_size)

    def onboard_payloads(
        self, host: str, port: int, block_hashes: List[int], max_size: int,
    ) -> List[Optional[bytes]]:
        """Batched onboard_payload: one multi-block round trip per chain
        instead of one per block — the DCN leg's unit of transfer."""
        return self.client.fetch_many(host, port, block_hashes, max_size)

    def onboard_payloads_hedged(
        self,
        addrs: List[Tuple[str, int]],
        block_hashes: List[int],
        max_size: int,
    ) -> List[Optional[bytes]]:
        """Batched onboard with fallback holders: primary first, hedge to
        the next holder on latency or failure (TransferClient semantics —
        first valid reply wins, never double-lands)."""
        return self.client.fetch_many_hedged(addrs, block_hashes, max_size)

    def fetch_staged(self, block_hash: int, max_size: int) -> Optional[bytes]:
        """Local host-store lookup; None if the block is not staged."""
        return self.onboard_payload("127.0.0.1", self.port, block_hash, max_size)

    def fetch_staged_many(
        self, block_hashes: List[int], max_size: int,
    ) -> List[Optional[bytes]]:
        """Batched local host-store lookup (one loopback round trip)."""
        return self.onboard_payloads("127.0.0.1", self.port, block_hashes,
                                     max_size)

    # -- cross-pod (DCN) -------------------------------------------------------

    def onboard(
        self, host: str, port: int, block_hash: int, like_k, like_v,
        token_ids=None, block_size: int = 0, parent_hash: Optional[int] = None,
    ) -> Optional[Tuple]:
        """Fetch a block from a remote pod and land it locally (+ event)."""
        payload = self.onboard_payload(
            host, port, block_hash, like_k.nbytes + like_v.nbytes
        )
        pages = self._decode(payload, like_k, like_v)
        if pages is not None and token_ids is not None:
            self._emit_stored(block_hash, token_ids, block_size, parent_hash,
                              self.config.device_tier_hbm)
        return pages

    # -- ICI (intra-slice) -----------------------------------------------------

    @staticmethod
    def transfer_ici(pages, sharding):
        """Move/replicate pages across devices of one mesh: XLA emits the ICI
        copies for the sharding change."""
        import jax

        return jax.device_put(pages, sharding)

    # -- internals ---------------------------------------------------------------

    @staticmethod
    def _decode(payload: Optional[bytes], like_k, like_v):
        if payload is None:
            return None
        if len(payload) != like_k.nbytes + like_v.nbytes:
            raise ValueError(
                f"block payload size {len(payload)} != expected "
                f"{like_k.nbytes + like_v.nbytes}"
            )
        k_np = np.frombuffer(payload[: like_k.nbytes], dtype=like_k.dtype).reshape(
            like_k.shape
        )
        v_np = np.frombuffer(payload[like_k.nbytes :], dtype=like_v.dtype).reshape(
            like_v.shape
        )
        return k_np, v_np

    def _emit_stored(self, block_hash, token_ids, block_size, parent_hash, tier,
                     lora_id=None):
        self._emit(EventBatch(ts=0.0, events=[
            BlockStored(
                block_hashes=[block_hash],
                parent_block_hash=parent_hash,
                token_ids=list(token_ids),
                block_size=block_size,
                lora_id=lora_id,
                medium=tier,
            )
        ]))

    def _emit(self, batch: EventBatch) -> None:
        if self.event_sink is not None:
            self.event_sink(batch)

    def close(self) -> None:
        self.drain_offloads()
        self.client.close()
        self.server.close()

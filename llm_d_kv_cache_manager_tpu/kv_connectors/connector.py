"""kv_connectors: the KV-block data plane (host staging + ICI/DCN transfer).

The reference plans but never implements this component
(/root/reference/kv_connectors/ holds only a .gitkeep; BASELINE.json's north
star requires "a TPU kv_connectors implementation that ships KV blocks
pod-to-pod over ICI/DCN"). This module is that implementation:

- **Host staging tier**: `KVConnector.offload` DMAs a page out of TPU HBM
  into host RAM (jax.device_get), registers it with the C++ transfer server
  (kv_connectors/cpp/kv_transfer.cpp), and emits BlockStored(medium="host")
  so the control plane scores the block at the host-tier weight.
  `restore` moves it back into HBM pages.
- **DCN / cross-pod leg**: `fetch_block` pulls a staged block from another
  pod's transfer server over TCP (the C++ engine; ctypes binding, no
  pybind11 in this image) and `KVConnector.onboard` lands it in local pages
  + emits BlockStored(medium="hbm").
- **ICI / intra-slice leg**: within one mesh, pages move device-to-device
  with `jax.device_put` / sharding constraints — XLA emits the ICI copies;
  `transfer_ici` wraps this.

Block wire format: raw little-endian bytes of the page pair, header-free —
the hash is the name, sizes come from the engine config on both ends.
"""

from __future__ import annotations

import ctypes
import os
from dataclasses import dataclass
from typing import Callable, Optional, Tuple

import numpy as np

from llm_d_kv_cache_manager_tpu.kvevents.events import (
    BlockRemoved,
    BlockStored,
    EventBatch,
)
from llm_d_kv_cache_manager_tpu.utils import logging as kvlog

logger = kvlog.get_logger("kv_connectors")

_LIB_PATHS = [
    os.path.join(os.path.dirname(__file__), "..", "..", "kv_connectors", "cpp",
                 "libkvtransfer.so"),
    "libkvtransfer.so",
]


def _load_lib() -> Optional[ctypes.CDLL]:
    for path in _LIB_PATHS:
        try:
            lib = ctypes.CDLL(os.path.abspath(path) if os.sep in path else path)
            break
        except OSError:
            continue
    else:
        return None
    lib.kvt_server_start.restype = ctypes.c_void_p
    lib.kvt_server_start.argtypes = [ctypes.c_int]
    lib.kvt_server_port.restype = ctypes.c_int
    lib.kvt_server_port.argtypes = [ctypes.c_void_p]
    lib.kvt_server_put.restype = ctypes.c_int
    lib.kvt_server_put.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_uint64,
    ]
    lib.kvt_server_remove.restype = ctypes.c_int
    lib.kvt_server_remove.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.kvt_server_block_count.restype = ctypes.c_uint64
    lib.kvt_server_block_count.argtypes = [ctypes.c_void_p]
    lib.kvt_server_stop.restype = None
    lib.kvt_server_stop.argtypes = [ctypes.c_void_p]
    lib.kvt_fetch.restype = ctypes.c_int64
    lib.kvt_fetch.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
    ]
    return lib


_lib = _load_lib()


def native_available() -> bool:
    return _lib is not None


class BlockTransferServer:
    """One pod's block-export endpoint (C++ engine, host-RAM store)."""

    def __init__(self, port: int = 0):
        if _lib is None:
            raise RuntimeError(
                "libkvtransfer.so not built — run `make -C kv_connectors/cpp`"
            )
        self._handle = _lib.kvt_server_start(port)
        if not self._handle:
            raise OSError(f"failed to start block transfer server on port {port}")

    @property
    def port(self) -> int:
        return _lib.kvt_server_port(self._handle)

    def put(self, block_hash: int, data: bytes) -> None:
        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
        if _lib.kvt_server_put(self._handle, block_hash & (2**64 - 1), buf, len(data)):
            raise OSError("kvt_server_put failed")

    def remove(self, block_hash: int) -> bool:
        return _lib.kvt_server_remove(self._handle, block_hash & (2**64 - 1)) == 0

    def block_count(self) -> int:
        return _lib.kvt_server_block_count(self._handle)

    def close(self) -> None:
        if self._handle:
            _lib.kvt_server_stop(self._handle)
            self._handle = None

    def __del__(self):  # noqa: D105
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass


def fetch_block(host: str, port: int, block_hash: int, max_size: int) -> Optional[bytes]:
    """Fetch a staged block from a remote pod. None if missing (a present but
    empty block returns b""); raises on transport error."""
    if _lib is None:
        raise RuntimeError("libkvtransfer.so not built")
    buf = (ctypes.c_uint8 * max(max_size, 1))()
    n = _lib.kvt_fetch(host.encode(), port, block_hash & (2**64 - 1), buf, max_size)
    if n == -2:
        return None
    if n < 0:
        raise OSError(f"kvt_fetch from {host}:{port} failed")
    return ctypes.string_at(buf, n)


@dataclass
class KVConnectorConfig:
    port: int = 0  # 0 -> ephemeral
    device_tier_hbm: str = "hbm"
    device_tier_host: str = "host"


class KVConnector:
    """Per-pod connector: moves KV pages between HBM, host staging, and
    remote pods, emitting the control-plane events for each move."""

    def __init__(
        self,
        config: KVConnectorConfig | None = None,
        event_sink: Optional[Callable[[EventBatch], None]] = None,
    ):
        self.config = config or KVConnectorConfig()
        self.server = BlockTransferServer(self.config.port)
        self.event_sink = event_sink

    @property
    def port(self) -> int:
        return self.server.port

    # -- HBM <-> host staging -------------------------------------------------

    def offload(
        self, block_hash: int, k_page, v_page, token_ids, block_size: int,
        parent_hash: Optional[int] = None,
    ) -> None:
        """Stage one page pair out of HBM into the host store (+ event)."""
        import jax

        k_np = np.asarray(jax.device_get(k_page))
        v_np = np.asarray(jax.device_get(v_page))
        self.stage(block_hash, k_np.tobytes() + v_np.tobytes(), token_ids,
                   block_size, parent_hash)

    def restore(self, block_hash: int, like_k, like_v) -> Optional[Tuple]:
        """Bring a host-staged block back as (k_page, v_page) arrays shaped
        like the given templates."""
        payload = self.fetch_staged(block_hash, like_k.nbytes + like_v.nbytes)
        return self._decode(payload, like_k, like_v)

    def drop(self, block_hash: int) -> None:
        if self.server.remove(block_hash):
            self._emit(EventBatch(ts=0.0, events=[
                BlockRemoved(block_hashes=[block_hash],
                             medium=self.config.device_tier_host)
            ]))

    # -- opaque-payload tier API (engine/tiering.py drives these) -------------

    def stage(
        self, block_hash: int, payload: bytes, token_ids, block_size: int,
        parent_hash: Optional[int] = None, lora_id: Optional[int] = None,
    ) -> None:
        """Stage an already-serialized block in the host store (+ host-tier
        BlockStored). The payload layout is the engine's business — the data
        plane treats blocks as opaque bytes named by their hash."""
        self.server.put(block_hash, payload)
        self._emit_stored(block_hash, token_ids, block_size, parent_hash,
                          self.config.device_tier_host, lora_id)

    def onboard_payload(
        self, host: str, port: int, block_hash: int, max_size: int,
    ) -> Optional[bytes]:
        """Pull a block's bytes from a pod's transfer server; None if absent.
        The caller lands it in HBM and the block manager emits the
        device-tier BlockStored, so no event fires here."""
        return fetch_block(host, port, block_hash, max_size)

    def fetch_staged(self, block_hash: int, max_size: int) -> Optional[bytes]:
        """Local host-store lookup; None if the block is not staged."""
        return self.onboard_payload("127.0.0.1", self.port, block_hash, max_size)

    # -- cross-pod (DCN) -------------------------------------------------------

    def onboard(
        self, host: str, port: int, block_hash: int, like_k, like_v,
        token_ids=None, block_size: int = 0, parent_hash: Optional[int] = None,
    ) -> Optional[Tuple]:
        """Fetch a block from a remote pod and land it locally (+ event)."""
        payload = fetch_block(host, port, block_hash, like_k.nbytes + like_v.nbytes)
        pages = self._decode(payload, like_k, like_v)
        if pages is not None and token_ids is not None:
            self._emit_stored(block_hash, token_ids, block_size, parent_hash,
                              self.config.device_tier_hbm)
        return pages

    # -- ICI (intra-slice) -----------------------------------------------------

    @staticmethod
    def transfer_ici(pages, sharding):
        """Move/replicate pages across devices of one mesh: XLA emits the ICI
        copies for the sharding change."""
        import jax

        return jax.device_put(pages, sharding)

    # -- internals ---------------------------------------------------------------

    @staticmethod
    def _decode(payload: Optional[bytes], like_k, like_v):
        if payload is None:
            return None
        if len(payload) != like_k.nbytes + like_v.nbytes:
            raise ValueError(
                f"block payload size {len(payload)} != expected "
                f"{like_k.nbytes + like_v.nbytes}"
            )
        k_np = np.frombuffer(payload[: like_k.nbytes], dtype=like_k.dtype).reshape(
            like_k.shape
        )
        v_np = np.frombuffer(payload[like_k.nbytes :], dtype=like_v.dtype).reshape(
            like_v.shape
        )
        return k_np, v_np

    def _emit_stored(self, block_hash, token_ids, block_size, parent_hash, tier,
                     lora_id=None):
        self._emit(EventBatch(ts=0.0, events=[
            BlockStored(
                block_hashes=[block_hash],
                parent_block_hash=parent_hash,
                token_ids=list(token_ids),
                block_size=block_size,
                lora_id=lora_id,
                medium=tier,
            )
        ]))

    def _emit(self, batch: EventBatch) -> None:
        if self.event_sink is not None:
            self.event_sink(batch)

    def close(self) -> None:
        self.server.close()

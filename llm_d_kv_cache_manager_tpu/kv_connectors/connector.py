"""kv_connectors: the KV-block data plane (host staging + ICI/DCN transfer).

The reference plans but never implements this component
(/root/reference/kv_connectors/ holds only a .gitkeep; BASELINE.json's north
star requires "a TPU kv_connectors implementation that ships KV blocks
pod-to-pod over ICI/DCN"). This module is that implementation:

- **Host staging tier**: `KVConnector.offload` DMAs a page out of TPU HBM
  into host RAM, registers it with the C++ transfer server
  (kv_connectors/cpp/kv_transfer.cpp), and emits BlockStored(medium="host")
  so the control plane scores the block at the host-tier weight. The
  pipelined form is `offload_async` + `drain_offloads`: the D2H copy is
  dispatched immediately (`copy_to_host_async`, overlapping queued compute)
  and a bounded completion queue pays only the residual sync at drain time.
  `restore` moves blocks back into HBM pages.
- **DCN / cross-pod leg**: `fetch_block`/`fetch_blocks` pull staged blocks
  from another pod's transfer server over TCP (the C++ engine; ctypes
  binding, no pybind11 in this image). The client side is a pooled
  keep-alive `TransferClient`: one persistent connection per peer, a
  multi-block request protocol (one round trip per chain, not per block),
  and bounded connect/read timeouts with retry — a dead peer costs a
  bounded timeout and a `transfer_failures` metric, never a hung socket.
  `KVConnector.onboard` lands fetched blocks in local pages + emits
  BlockStored(medium="hbm").
- **ICI / intra-slice leg**: within one mesh, pages move device-to-device
  with `jax.device_put` / sharding constraints — XLA emits the ICI copies;
  `transfer_ici` wraps this.

Block wire format: raw little-endian bytes of the page pair, header-free —
the hash is the name, sizes come from the engine config on both ends.
"""

from __future__ import annotations

import ctypes
import os
import threading
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from llm_d_kv_cache_manager_tpu import obs
from llm_d_kv_cache_manager_tpu.kvevents.events import (
    BlockRemoved,
    BlockStored,
    EventBatch,
)
from llm_d_kv_cache_manager_tpu.metrics import collector as metrics
from llm_d_kv_cache_manager_tpu.utils import logging as kvlog

logger = kvlog.get_logger("kv_connectors")

# Override the transfer-engine library location (absolute path to
# libkvtransfer.so). Takes precedence over the checkout/package locations.
_LIB_ENV = "KVTPU_TRANSFER_LIB"


def _candidate_lib_paths() -> List[str]:
    """Absolute candidate paths for libkvtransfer.so, most specific first.
    Never a bare soname: a bare "libkvtransfer.so" would let a stale copy
    on the system loader path silently shadow the checkout's build."""
    paths = []
    env = os.environ.get(_LIB_ENV)
    if env:
        paths.append(os.path.abspath(env))
    # Repo-checkout layout: <repo>/kv_connectors/cpp/libkvtransfer.so.
    paths.append(os.path.abspath(os.path.join(
        os.path.dirname(__file__), "..", "..", "kv_connectors", "cpp",
        "libkvtransfer.so",
    )))
    # Installed-package layout: the .so shipped alongside this module
    # (importlib.resources resolves the package dir wherever it landed).
    try:
        from importlib import resources

        pkg = resources.files("llm_d_kv_cache_manager_tpu.kv_connectors")
        paths.append(str(pkg / "libkvtransfer.so"))
    except Exception:  # noqa: BLE001 - resources API absent/odd installs
        paths.append(os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "libkvtransfer.so"
        ))
    return paths


def _load_lib() -> Optional[ctypes.CDLL]:
    for path in _candidate_lib_paths():
        if not os.path.exists(path):
            continue
        try:
            lib = ctypes.CDLL(path)
        except OSError as e:
            logger.warning("found %s but could not load it: %s", path, e)
            continue
        _configure_lib(lib)
        logger.info("kv transfer engine loaded from %s", path)
        return lib
    logger.debug(
        "libkvtransfer.so not found (searched %s) — transfer plane disabled",
        _candidate_lib_paths(),
    )
    return None


def _configure_lib(lib: ctypes.CDLL) -> None:
    lib.kvt_server_start.restype = ctypes.c_void_p
    lib.kvt_server_start.argtypes = [ctypes.c_int]
    lib.kvt_server_port.restype = ctypes.c_int
    lib.kvt_server_port.argtypes = [ctypes.c_void_p]
    lib.kvt_server_put.restype = ctypes.c_int
    lib.kvt_server_put.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_uint64,
    ]
    lib.kvt_server_remove.restype = ctypes.c_int
    lib.kvt_server_remove.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.kvt_server_block_count.restype = ctypes.c_uint64
    lib.kvt_server_block_count.argtypes = [ctypes.c_void_p]
    lib.kvt_server_stop.restype = None
    lib.kvt_server_stop.argtypes = [ctypes.c_void_p]
    lib.kvt_fetch.restype = ctypes.c_int64
    lib.kvt_fetch.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
    ]
    # Pooled-client API (this build). Guarded so a stale .so from an older
    # build still serves the single-block legacy path instead of failing
    # at import; the batched paths then degrade to per-block fetches.
    if hasattr(lib, "kvt_fetch_many"):
        lib.kvt_connect.restype = ctypes.c_int
        lib.kvt_connect.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
        lib.kvt_close.restype = None
        lib.kvt_close.argtypes = [ctypes.c_int]
        lib.kvt_fetch_conn.restype = ctypes.c_int64
        lib.kvt_fetch_conn.argtypes = [
            ctypes.c_int, ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_uint64, ctypes.c_int,
        ]
        lib.kvt_fetch_many.restype = ctypes.c_int
        lib.kvt_fetch_many.argtypes = [
            ctypes.c_int, ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ]


_lib = _load_lib()


def native_available() -> bool:
    return _lib is not None


def client_api_available() -> bool:
    """True when the loaded .so carries the pooled/batched client ABI."""
    return _lib is not None and hasattr(_lib, "kvt_fetch_many")


class BlockTransferServer:
    """One pod's block-export endpoint (C++ engine, host-RAM store)."""

    def __init__(self, port: int = 0):
        if _lib is None:
            raise RuntimeError(
                "libkvtransfer.so not built — run `make kvtransfer`"
            )
        self._handle = _lib.kvt_server_start(port)
        if not self._handle:
            raise OSError(f"failed to start block transfer server on port {port}")

    @property
    def port(self) -> int:
        return _lib.kvt_server_port(self._handle)

    def put(self, block_hash: int, data: bytes) -> None:
        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
        if _lib.kvt_server_put(self._handle, block_hash & (2**64 - 1), buf, len(data)):
            raise OSError("kvt_server_put failed")

    def remove(self, block_hash: int) -> bool:
        return _lib.kvt_server_remove(self._handle, block_hash & (2**64 - 1)) == 0

    def block_count(self) -> int:
        return _lib.kvt_server_block_count(self._handle)

    def close(self) -> None:
        if self._handle:
            _lib.kvt_server_stop(self._handle)
            self._handle = None

    def __del__(self):  # noqa: D105
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass


# -- pooled keep-alive DCN client ---------------------------------------------


@dataclass
class TransferClientConfig:
    connect_timeout_ms: int = 2000
    io_timeout_ms: int = 5000
    # Reconnect-and-retry attempts after a transport error/timeout (the
    # request is idempotent — a fetch has no side effects — so a retry can
    # never double-apply anything).
    retries: int = 1
    # Blocks per wire request; longer chains split into multiple round
    # trips (still 1/max_batch of the serial count).
    max_batch: int = 256


class _Conn:
    __slots__ = ("fd", "lock")

    def __init__(self):
        self.fd = -1
        self.lock = threading.Lock()


class TransferClient:
    """Pooled keep-alive fetch client for the DCN leg.

    One persistent connection per (host, port); `fetch_many` moves a whole
    chain in one round trip through the C++ multi-block protocol. Every
    operation is bounded by connect/read timeouts and a bounded retry —
    on exhaustion the blocks come back as None (a miss the tiering layer
    already handles) and `transfer_failures` counts the event, so a dead
    peer can never wedge the serving thread on a stuck socket.
    """

    def __init__(self, config: Optional[TransferClientConfig] = None):
        self.config = config or TransferClientConfig()
        self._pool: Dict[Tuple[str, int], _Conn] = {}
        self._mu = threading.Lock()  # pool map only
        self.stats: Dict[str, int] = {
            "connects": 0, "reconnects": 0, "failures": 0,
            "batch_fetches": 0, "blocks_fetched": 0,
        }

    def _conn(self, host: str, port: int) -> _Conn:
        with self._mu:
            conn = self._pool.get((host, port))
            if conn is None:
                conn = self._pool[(host, port)] = _Conn()
            return conn

    def _ensure_connected(self, conn: _Conn, host: str, port: int) -> bool:
        if conn.fd >= 0:
            return True
        conn.fd = _lib.kvt_connect(
            host.encode(), port, self.config.connect_timeout_ms
        )
        if conn.fd >= 0:
            self.stats["connects"] += 1
            return True
        return False

    def _drop(self, conn: _Conn) -> None:
        if conn.fd >= 0:
            _lib.kvt_close(conn.fd)
            conn.fd = -1

    def _fail(self, host: str, port: int, n: int, what: str) -> None:
        self.stats["failures"] += 1
        metrics.count_transfer_failure()
        logger.warning(
            "transfer %s from %s:%d failed after %d attempt(s) (%d block(s) "
            "treated as missing)", what, host, port,
            self.config.retries + 1, n,
        )

    def fetch_one(
        self, host: str, port: int, block_hash: int, max_size: int,
    ) -> Optional[bytes]:
        """One block over the pooled connection. None when missing remotely
        OR when every attempt failed (counted in `transfer_failures`)."""
        if not client_api_available():
            return _legacy_fetch(host, port, block_hash, max_size)
        cap = max(max_size, 1)
        buf = (ctypes.c_uint8 * cap)()
        conn = self._conn(host, port)
        # Peer identity rides the trace META (data), never a metric label.
        obs.annotate("peer", f"{host}:{port}")
        with obs.stage("transfer.dcn_fetch"), conn.lock:
            for attempt in range(self.config.retries + 1):
                if attempt:
                    self.stats["reconnects"] += 1
                if not self._ensure_connected(conn, host, port):
                    continue
                n = _lib.kvt_fetch_conn(
                    conn.fd, block_hash & (2**64 - 1), buf, cap,
                    self.config.io_timeout_ms,
                )
                if n == -2:
                    return None  # present nowhere — a genuine miss
                if n >= 0:
                    return ctypes.string_at(buf, n)
                self._drop(conn)  # transport error: reconnect and retry
        self._fail(host, port, 1, "fetch")
        return None

    def fetch_many(
        self, host: str, port: int, block_hashes: List[int], max_size: int,
    ) -> List[Optional[bytes]]:
        """Fetch a chain in one round trip per `max_batch` blocks. Returns
        payloads aligned with `block_hashes`; None marks a block missing
        remotely or lost to a (bounded, retried, counted) transport
        failure."""
        if not block_hashes:
            return []
        if not client_api_available():
            return [
                _legacy_fetch(host, port, h, max_size) for h in block_hashes
            ]
        out: List[Optional[bytes]] = []
        mb = max(1, self.config.max_batch)
        for i in range(0, len(block_hashes), mb):
            out.extend(
                self._fetch_chunk(host, port, block_hashes[i:i + mb], max_size)
            )
        return out

    def _fetch_chunk(
        self, host: str, port: int, hashes: List[int], max_size: int,
    ) -> List[Optional[bytes]]:
        n = len(hashes)
        cap = max(max_size, 1)
        arr = (ctypes.c_uint64 * n)(*[h & (2**64 - 1) for h in hashes])
        buf = (ctypes.c_uint8 * (n * cap))()
        lens = (ctypes.c_int64 * n)()
        conn = self._conn(host, port)
        obs.annotate("peer", f"{host}:{port}")
        with obs.stage("transfer.dcn_fetch"), conn.lock:
            for attempt in range(self.config.retries + 1):
                if attempt:
                    self.stats["reconnects"] += 1
                if not self._ensure_connected(conn, host, port):
                    continue
                rc = _lib.kvt_fetch_many(
                    conn.fd, n, arr, buf, cap, lens, self.config.io_timeout_ms
                )
                if rc == 0:
                    self.stats["batch_fetches"] += 1
                    self.stats["blocks_fetched"] += n
                    base = ctypes.addressof(buf)
                    result: List[Optional[bytes]] = []
                    for i in range(n):
                        ln = lens[i]
                        if ln >= 0:
                            result.append(
                                ctypes.string_at(base + i * cap, ln)
                            )
                        else:
                            if ln == -3:
                                logger.warning(
                                    "block %x from %s:%d exceeds cap %d — "
                                    "dropped", hashes[i], host, port, cap,
                                )
                            result.append(None)
                    return result
                self._drop(conn)
        self._fail(host, port, n, "batch fetch")
        return [None] * n

    def close(self) -> None:
        with self._mu:
            conns = list(self._pool.values())
            self._pool.clear()
        for conn in conns:
            with conn.lock:
                self._drop(conn)


_default_client: Optional[TransferClient] = None
_default_client_mu = threading.Lock()


def default_client() -> TransferClient:
    """Process-wide pooled client (module-level fetch_block/fetch_blocks)."""
    global _default_client
    with _default_client_mu:
        if _default_client is None:
            _default_client = TransferClient()
        return _default_client


def _legacy_fetch(
    host: str, port: int, block_hash: int, max_size: int,
) -> Optional[bytes]:
    """Throwaway-connection fetch via the old ABI (stale .so builds). No
    timeout bound — exactly the seed behavior this PR replaces."""
    buf = (ctypes.c_uint8 * max(max_size, 1))()
    n = _lib.kvt_fetch(host.encode(), port, block_hash & (2**64 - 1), buf, max_size)
    if n == -2:
        return None
    if n < 0:
        metrics.count_transfer_failure()
        logger.warning("legacy fetch from %s:%d failed", host, port)
        return None
    return ctypes.string_at(buf, n)


def fetch_block(host: str, port: int, block_hash: int, max_size: int) -> Optional[bytes]:
    """Fetch a staged block from a pod over the pooled keep-alive client.
    None if the block is missing (a present-but-empty block returns b"") OR
    if the transfer failed after the bounded timeout/retry budget — the
    failure is logged and counted (`transfer_failures`), never raised, so a
    dead peer degrades to a cache miss instead of an unbounded hang."""
    if _lib is None:
        raise RuntimeError("libkvtransfer.so not built")
    return default_client().fetch_one(host, port, block_hash, max_size)


def fetch_blocks(
    host: str, port: int, block_hashes: List[int], max_size: int,
) -> List[Optional[bytes]]:
    """Batched `fetch_block`: one round trip per chain (multi-block wire
    protocol). Same None semantics per block."""
    if _lib is None:
        raise RuntimeError("libkvtransfer.so not built")
    return default_client().fetch_many(host, port, block_hashes, max_size)


@dataclass
class KVConnectorConfig:
    port: int = 0  # 0 -> ephemeral
    device_tier_hbm: str = "hbm"
    device_tier_host: str = "host"
    # Completion-queue bound for offload_async: at most this many dispatched
    # D2H snapshots awaiting drain (each holds its device buffers alive);
    # dispatching past the bound drains the oldest entry first.
    max_inflight_offloads: int = 16
    # DCN client bounds (threaded into this connector's TransferClient).
    connect_timeout_ms: int = 2000
    fetch_timeout_ms: int = 5000
    fetch_retries: int = 1
    fetch_batch_size: int = 256


class KVConnector:
    """Per-pod connector: moves KV pages between HBM, host staging, and
    remote pods, emitting the control-plane events for each move."""

    def __init__(
        self,
        config: KVConnectorConfig | None = None,
        event_sink: Optional[Callable[[EventBatch], None]] = None,
    ):
        self.config = config or KVConnectorConfig()
        self.server = BlockTransferServer(self.config.port)
        self.event_sink = event_sink
        self.client = TransferClient(TransferClientConfig(
            connect_timeout_ms=self.config.connect_timeout_ms,
            io_timeout_ms=self.config.fetch_timeout_ms,
            retries=self.config.fetch_retries,
            max_batch=self.config.fetch_batch_size,
        ))
        # Dispatched-but-undrained offload snapshots, FIFO. Entries hold
        # the device arrays whose copy_to_host_async is in flight.
        self._offloads: deque = deque()
        self._offload_mu = threading.Lock()

    @property
    def port(self) -> int:
        return self.server.port

    # -- HBM <-> host staging -------------------------------------------------

    def offload(
        self, block_hash: int, k_page, v_page, token_ids, block_size: int,
        parent_hash: Optional[int] = None,
    ) -> None:
        """Stage one page pair out of HBM into the host store (+ event).
        Synchronous form: dispatches the D2H copy and drains the whole
        completion queue (older async offloads included) before returning."""
        self.offload_async(block_hash, k_page, v_page, token_ids, block_size,
                           parent_hash)
        self.drain_offloads()

    def offload_async(
        self, block_hash: int, k_page, v_page, token_ids, block_size: int,
        parent_hash: Optional[int] = None, lora_id: Optional[int] = None,
    ) -> None:
        """Dispatch a page pair's D2H copy NOW and return: the DMA overlaps
        whatever compute is queued behind it, and the block is staged (+
        host-tier event) when `drain_offloads` resolves the completion
        queue. The snapshot is content-stable — the copy consumes the pages
        in enqueue order, so later device writes cannot corrupt it. Past
        `max_inflight_offloads`, the oldest entry is drained first (bounded
        memory, still pipelined)."""
        with obs.stage("transfer.offload_dispatch"):
            for page in (k_page, v_page):
                try:
                    # On the CPU backend there is no DMA engine to overlap:
                    # copy_to_host_async degenerates to a synchronous
                    # memcpy, which would move the whole copy ONTO the
                    # dispatch path — the opposite of the point. Skip the
                    # hint there; the drain's device_get pays the same
                    # memcpy off the critical path instead.
                    if next(iter(page.devices())).platform != "cpu":
                        page.copy_to_host_async()
                except Exception:  # noqa: BLE001 - hint; device_get works
                    pass
            entry = (block_hash, k_page, v_page, list(token_ids), block_size,
                     parent_hash, lora_id)
            drain_oldest = []
            with self._offload_mu:
                self._offloads.append(entry)
                while len(self._offloads) > max(
                    1, self.config.max_inflight_offloads
                ):
                    drain_oldest.append(self._offloads.popleft())
        for old in drain_oldest:
            self._resolve_offload(old)

    def drain_offloads(self, max_blocks: Optional[int] = None) -> List[int]:
        """Resolve pending offload snapshots (oldest first): wait out the
        residual D2H sync, stage the bytes, emit the host-tier event.
        Returns the staged block hashes in dispatch order."""
        done: List[int] = []
        while max_blocks is None or len(done) < max_blocks:
            with self._offload_mu:
                if not self._offloads:
                    break
                entry = self._offloads.popleft()
            self._resolve_offload(entry)
            done.append(entry[0])
        return done

    @property
    def pending_offloads(self) -> int:
        with self._offload_mu:
            return len(self._offloads)

    def _resolve_offload(self, entry) -> None:
        import jax

        with obs.stage("transfer.offload_drain"):
            block_hash, k_page, v_page, token_ids, block_size, parent, lora = entry
            k_np = np.asarray(jax.device_get(k_page))
            v_np = np.asarray(jax.device_get(v_page))
            self.stage(block_hash, k_np.tobytes() + v_np.tobytes(), token_ids,
                       block_size, parent, lora)

    def restore(self, block_hash: int, like_k, like_v) -> Optional[Tuple]:
        """Bring a host-staged block back as (k_page, v_page) arrays shaped
        like the given templates."""
        payload = self.fetch_staged(block_hash, like_k.nbytes + like_v.nbytes)
        return self._decode(payload, like_k, like_v)

    def drop(self, block_hash: int) -> None:
        if self.server.remove(block_hash):
            self._emit(EventBatch(ts=0.0, events=[
                BlockRemoved(block_hashes=[block_hash],
                             medium=self.config.device_tier_host)
            ]))

    # -- opaque-payload tier API (engine/tiering.py drives these) -------------

    def stage(
        self, block_hash: int, payload: bytes, token_ids, block_size: int,
        parent_hash: Optional[int] = None, lora_id: Optional[int] = None,
    ) -> None:
        """Stage an already-serialized block in the host store (+ host-tier
        BlockStored). The payload layout is the engine's business — the data
        plane treats blocks as opaque bytes named by their hash."""
        self.server.put(block_hash, payload)
        self._emit_stored(block_hash, token_ids, block_size, parent_hash,
                          self.config.device_tier_host, lora_id)

    def onboard_payload(
        self, host: str, port: int, block_hash: int, max_size: int,
    ) -> Optional[bytes]:
        """Pull a block's bytes from a pod's transfer server; None if absent
        or the transfer failed its bounded retry. The caller lands it in
        HBM and the block manager emits the device-tier BlockStored, so no
        event fires here."""
        return self.client.fetch_one(host, port, block_hash, max_size)

    def onboard_payloads(
        self, host: str, port: int, block_hashes: List[int], max_size: int,
    ) -> List[Optional[bytes]]:
        """Batched onboard_payload: one multi-block round trip per chain
        instead of one per block — the DCN leg's unit of transfer."""
        return self.client.fetch_many(host, port, block_hashes, max_size)

    def fetch_staged(self, block_hash: int, max_size: int) -> Optional[bytes]:
        """Local host-store lookup; None if the block is not staged."""
        return self.onboard_payload("127.0.0.1", self.port, block_hash, max_size)

    def fetch_staged_many(
        self, block_hashes: List[int], max_size: int,
    ) -> List[Optional[bytes]]:
        """Batched local host-store lookup (one loopback round trip)."""
        return self.onboard_payloads("127.0.0.1", self.port, block_hashes,
                                     max_size)

    # -- cross-pod (DCN) -------------------------------------------------------

    def onboard(
        self, host: str, port: int, block_hash: int, like_k, like_v,
        token_ids=None, block_size: int = 0, parent_hash: Optional[int] = None,
    ) -> Optional[Tuple]:
        """Fetch a block from a remote pod and land it locally (+ event)."""
        payload = self.onboard_payload(
            host, port, block_hash, like_k.nbytes + like_v.nbytes
        )
        pages = self._decode(payload, like_k, like_v)
        if pages is not None and token_ids is not None:
            self._emit_stored(block_hash, token_ids, block_size, parent_hash,
                              self.config.device_tier_hbm)
        return pages

    # -- ICI (intra-slice) -----------------------------------------------------

    @staticmethod
    def transfer_ici(pages, sharding):
        """Move/replicate pages across devices of one mesh: XLA emits the ICI
        copies for the sharding change."""
        import jax

        return jax.device_put(pages, sharding)

    # -- internals ---------------------------------------------------------------

    @staticmethod
    def _decode(payload: Optional[bytes], like_k, like_v):
        if payload is None:
            return None
        if len(payload) != like_k.nbytes + like_v.nbytes:
            raise ValueError(
                f"block payload size {len(payload)} != expected "
                f"{like_k.nbytes + like_v.nbytes}"
            )
        k_np = np.frombuffer(payload[: like_k.nbytes], dtype=like_k.dtype).reshape(
            like_k.shape
        )
        v_np = np.frombuffer(payload[like_k.nbytes :], dtype=like_v.dtype).reshape(
            like_v.shape
        )
        return k_np, v_np

    def _emit_stored(self, block_hash, token_ids, block_size, parent_hash, tier,
                     lora_id=None):
        self._emit(EventBatch(ts=0.0, events=[
            BlockStored(
                block_hashes=[block_hash],
                parent_block_hash=parent_hash,
                token_ids=list(token_ids),
                block_size=block_size,
                lora_id=lora_id,
                medium=tier,
            )
        ]))

    def _emit(self, batch: EventBatch) -> None:
        if self.event_sink is not None:
            self.event_sink(batch)

    def close(self) -> None:
        self.drain_offloads()
        self.client.close()
        self.server.close()

from llm_d_kv_cache_manager_tpu.kv_connectors.connector import (
    BlockTransferServer,
    KVConnector,
    KVConnectorConfig,
    fetch_block,
)

__all__ = [
    "BlockTransferServer",
    "KVConnector",
    "KVConnectorConfig",
    "fetch_block",
]

from llm_d_kv_cache_manager_tpu.kv_connectors.connector import (
    BlockTransferServer,
    KVConnector,
    KVConnectorConfig,
    PeerBreaker,
    TransferClient,
    TransferClientConfig,
    fetch_block,
)
from llm_d_kv_cache_manager_tpu.kv_connectors.faults import (
    FaultyTransport,
    PeerTransferFaults,
    TransferFaultPlan,
)

__all__ = [
    "BlockTransferServer",
    "FaultyTransport",
    "KVConnector",
    "KVConnectorConfig",
    "PeerBreaker",
    "PeerTransferFaults",
    "TransferClient",
    "TransferClientConfig",
    "TransferFaultPlan",
    "fetch_block",
]

"""Deterministic fault injection for the KV-block TRANSFER plane.

The write-plane injector (fleethealth/faults.py) chaos-tests the event
seam; this module does the same for the data plane. Faults are injected at
the transfer-client seam — the exact boundary where `TieredKVStore` and the
prefetcher hand fetches to a `TransferClient` — so everything downstream of
a fault (integrity fallback, per-peer breakers, hedged fetches, chain-cut
recompute, the counters) is the REAL code path under test.

Fault classes (per peer address, composable, clock-windowed):

- **corrupt**: a fetched block is corrupted iff a seeded hash of
  (plan seed, peer, block hash) falls under `corrupt_rate` — a
  deterministic "bad cells" model: the same blocks are always the damaged
  ones, independent of fetch order, so a chaos run replays bit-for-bit
  even though the event pool's worker interleaving varies. With integrity
  verification ON (the default) the corruption is *detected*: the block
  degrades to a miss through `TransferClient.note_result` — the same seam
  the C++ client's checksum mismatch reports through — and the breaker
  learns about it. With verification OFF the corrupted payload is
  DELIVERED and counted in `corrupt_admitted`: the silent wrong-KV-bytes
  failure mode the end-to-end checksum exists to kill (the chaos bench's
  control arm).
- **stall**: fetches in the window hang until the IO timeout ladder
  expires, then fail. The injector synthesizes the outcome instantly but
  charges the full `io_timeout * attempts` latency through `charge_s` (the
  bench adds it to the serving clock) and reports the failure to the
  breaker — which is what makes "breaker open ⇒ skip instantly" measurable.
- **blackhole**: connects hang (packets dropped); same shape with the
  connect-timeout ladder.
- **flap**: the peer alternates up/down with `flap_period_s` /
  `flap_down_frac` — the breaker's half-open probe recovery is exercised on
  every up transition.

Everything is driven by an injected clock and seeded hashing, so a chaos
run is a pure function of (plan, workload) and replays bit-for-bit.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from llm_d_kv_cache_manager_tpu.utils import logging as kvlog

logger = kvlog.get_logger("kv_connectors.faults")

Addr = Tuple[str, int]


@dataclass
class PeerTransferFaults:
    # Independent per-block corruption probability inside the window.
    corrupt_rate: float = 0.0
    corrupt_from_s: float = 0.0
    corrupt_until_s: Optional[float] = None
    # Stall window: fetches pay the full IO-timeout ladder and fail.
    stall_from_s: Optional[float] = None
    stall_until_s: Optional[float] = None
    # Blackhole window: connects pay the connect-timeout ladder and fail.
    blackhole_from_s: Optional[float] = None
    blackhole_until_s: Optional[float] = None
    # Flapping: from `flap_from_s`, the peer is DOWN for the first
    # `flap_down_frac` of every `flap_period_s` cycle (down = stall-like).
    flap_from_s: Optional[float] = None
    flap_period_s: float = 10.0
    flap_down_frac: float = 0.5

    def corrupting(self, now: float) -> bool:
        return (
            self.corrupt_rate > 0.0
            and now >= self.corrupt_from_s
            and (self.corrupt_until_s is None or now < self.corrupt_until_s)
        )

    def stalled(self, now: float) -> bool:
        if (
            self.stall_from_s is not None
            and self.stall_from_s <= now
            and (self.stall_until_s is None or now < self.stall_until_s)
        ):
            return True
        if self.flap_from_s is not None and now >= self.flap_from_s:
            phase = (now - self.flap_from_s) % max(self.flap_period_s, 1e-9)
            return phase < self.flap_down_frac * self.flap_period_s
        return False

    def blackholed(self, now: float) -> bool:
        return (
            self.blackhole_from_s is not None
            and self.blackhole_from_s <= now
            and (
                self.blackhole_until_s is None
                or now < self.blackhole_until_s
            )
        )

    def as_dict(self) -> dict:
        out = {}
        for k, v in (
            ("corrupt_rate", self.corrupt_rate),
            ("corrupt_from_s", self.corrupt_from_s),
            ("corrupt_until_s", self.corrupt_until_s),
            ("stall_from_s", self.stall_from_s),
            ("stall_until_s", self.stall_until_s),
            ("blackhole_from_s", self.blackhole_from_s),
            ("blackhole_until_s", self.blackhole_until_s),
            ("flap_from_s", self.flap_from_s),
        ):
            if v not in (None, 0.0):
                out[k] = v
        if self.flap_from_s is not None:
            out["flap_period_s"] = self.flap_period_s
            out["flap_down_frac"] = self.flap_down_frac
        return out


@dataclass
class TransferFaultPlan:
    seed: int = 0
    peers: Dict[Addr, PeerTransferFaults] = field(default_factory=dict)

    def for_peer(self, addr: Addr) -> Optional[PeerTransferFaults]:
        return self.peers.get(addr)

    def as_dict(self) -> dict:
        """JSON-serializable provenance for bench artifacts."""
        return {
            "seed": self.seed,
            "peers": {
                f"{host}:{port}": faults.as_dict()
                for (host, port), faults in sorted(self.peers.items())
            },
        }


class FaultyTransport:
    """A TransferClient wrapper applying a TransferFaultPlan at the fetch
    seam.

    Fault-free peers (and the pod's own loopback address, `self_addr`)
    pass straight through to the inner client — the healthy path stays
    bit-identical. Faulted fetches synthesize the outcome a real flaky
    NIC/wire would produce and report it through the inner client's
    bookkeeping seam (`note_result` / the breaker gate), so breakers,
    latency EWMAs, and every counter behave exactly as they would against
    real damage, while the simulated clock charges the latency the real
    damage would have cost (`charge_s`, drained by the bench into the
    serving clock; `fetch_log` keeps per-fetch (t, peer, latency, outcome)
    rows for tail-latency analysis).
    """

    def __init__(
        self,
        inner,
        plan: TransferFaultPlan,
        clock,
        self_addr: Optional[Addr] = None,
        verify_integrity: bool = True,
    ):
        self.inner = inner
        self.plan = plan
        self.clock = clock
        self.self_addr = self_addr
        self.verify_integrity = verify_integrity
        self.charge_s = 0.0  # un-drained synthetic latency (take_charge)
        self.fetch_log: List[tuple] = []  # (t, "host:port", latency_s, kind)
        self.counters = {
            "corrupt_injected": 0,
            "corrupt_detected": 0,
            "corrupt_admitted": 0,
            "stalled_fetches": 0,
            "blackholed_fetches": 0,
            "breaker_skipped_fetches": 0,
        }

    # -- plumbing ----------------------------------------------------------

    def _block_corrupted(self, block_hash: int, rate: float) -> bool:
        """Deterministic per-(seed, block) corruption draw: the same
        blocks are always the damaged ones ("bad cells"), so injected
        damage is independent of fetch order, retries, worker
        interleaving, and the peers' EPHEMERAL ports — a chaos run
        replays bit-for-bit. Which peers damage anything at all is the
        plan's per-peer corrupt_rate/window."""
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.hashing import (
            fnv64a,
        )

        draw = fnv64a(f"{self.plan.seed}|{block_hash:x}".encode())
        return draw < rate * float(1 << 64)

    def _charge(self, addr: Addr, latency_s: float, kind: str) -> None:
        self.charge_s += latency_s
        self.fetch_log.append(
            (self.clock(), f"{addr[0]}:{addr[1]}", latency_s, kind)
        )

    def take_charge(self) -> float:
        """Drain the accumulated synthetic latency (the bench adds it to
        the serving clock after each request)."""
        out, self.charge_s = self.charge_s, 0.0
        return out

    def _timeout_ladder_s(self, connect: bool) -> float:
        cfg = self.inner.config
        per = (
            cfg.connect_timeout_ms if connect else cfg.io_timeout_ms
        ) / 1e3
        return per * (cfg.retries + 1)

    def _down_fetch(
        self, addr: Addr, n: int, kind: str, connect: bool
    ) -> List[None]:
        """Synthesize a dead-peer fetch: breaker-gated (an open breaker
        skips instantly — the whole point), else pay the timeout ladder
        and report the failure."""
        if not self.inner.allow_peer(*addr):
            self.counters["breaker_skipped_fetches"] += 1
            self._charge(addr, 0.0, "breaker_skip")
            # Count the skip the same way the real gate does.
            self.inner._breaker_skip(addr[0], addr[1], n)  # noqa: SLF001
            return [None] * n
        latency = self._timeout_ladder_s(connect)
        self.counters[f"{kind}_fetches"] += 1
        self._charge(addr, latency, kind)
        self.inner.note_result(
            addr[0], addr[1], ok=False, latency_s=latency, blocks=n
        )
        self.inner._fail(  # noqa: SLF001 - same log/metric as a real fail
            addr[0], addr[1], n, f"batch fetch ({kind} injected)"
        )
        return [None] * n

    # -- TransferClient surface -------------------------------------------

    def fetch_one(self, host, port, block_hash, max_size):
        return self.fetch_many(host, port, [block_hash], max_size)[0]

    def fetch_many(self, host, port, block_hashes, max_size):
        if not block_hashes:
            return []
        addr = (host, port)
        faults = (
            None if addr == self.self_addr else self.plan.for_peer(addr)
        )
        now = self.clock()
        if faults is not None and faults.blackholed(now):
            return self._down_fetch(
                addr, len(block_hashes), "blackholed", connect=True
            )
        if faults is not None and faults.stalled(now):
            return self._down_fetch(
                addr, len(block_hashes), "stalled", connect=False
            )
        result = self.inner.fetch_many(host, port, block_hashes, max_size)
        if faults is None or not faults.corrupting(now):
            return result
        corrupted = 0
        out = []
        for block_hash, payload in zip(block_hashes, result):
            if payload is not None and self._block_corrupted(
                block_hash, faults.corrupt_rate
            ):
                corrupted += 1
                if self.verify_integrity:
                    # Detected at the client edge (the C++ checksum seam):
                    # the block degrades to a miss, never lands.
                    out.append(None)
                else:
                    # v1 wire: the damage sails through — the engine lands
                    # wrong KV bytes and serves wrong output. Counted so
                    # the control arm can show what integrity prevents.
                    self.counters["corrupt_admitted"] += 1
                    out.append(payload)
            else:
                out.append(payload)
        if corrupted:
            self.counters["corrupt_injected"] += corrupted
            if self.verify_integrity:
                self.counters["corrupt_detected"] += corrupted
                # Report through the SAME seam a real checksum mismatch
                # uses: corrupt counters + breaker failure.
                self.inner.note_result(
                    host, port, ok=True, latency_s=0.0,
                    corrupt_blocks=corrupted, blocks=len(block_hashes),
                )
        return out

    def fetch_many_hedged(self, addrs, block_hashes, max_size):
        """Hedged form: faults apply per underlying fetch (each holder is
        fetched through THIS wrapper), so a corrupt/stalled primary loses
        the race to a healthy alternate exactly as it would in production.
        Synchronous fallback chain — the sim clock cannot overlap real
        threads, so the hedge's win is modeled as 'next holder pays its
        own (possibly zero-fault) fetch', with the primary's charge kept
        (the hedge delay the serving thread actually waited)."""
        if not block_hashes:
            return []
        best = None
        best_cover = -1
        for i, addr in enumerate(addrs):
            result = self.fetch_many(
                addr[0], addr[1], list(block_hashes), max_size
            )
            cover = sum(payload is not None for payload in result)
            if cover > best_cover:
                best, best_cover = result, cover
            if cover == len(block_hashes):
                if i > 0:
                    self.inner.stats["hedges"] += i
                    self.inner.stats["hedge_wins"] += 1
                return result
        if best is None:
            return [None] * len(block_hashes)
        if len(addrs) > 1:
            self.inner.stats["hedges"] += len(addrs) - 1
        return best

    def close(self):
        self.inner.close()

    # Introspection passthroughs (the /readyz + bench surfaces).
    @property
    def stats(self):
        return self.inner.stats

    @property
    def config(self):
        return self.inner.config

    def status(self):
        out = self.inner.status()
        out["injected_faults"] = dict(self.counters)
        return out

    def peer_state(self, host, port):
        return self.inner.peer_state(host, port)

    def allow_peer(self, host, port):
        return self.inner.allow_peer(host, port)

    def note_result(self, *args, **kwargs):
        return self.inner.note_result(*args, **kwargs)

"""Route-driven prefetch queue: close the router→data-plane loop.

The read path already computes, per pod, the longest cached prefix of every
routed prompt (`Indexer.get_pod_scores_ex`). The moment the router picks a
pod, the exact set of blocks that pod will MISS — the tail of the chain past
its matched prefix — is known, minutes of compute before the engine's
allocator faults on it. The seed threw that information away; this module
feeds it to the chosen pod's prefetcher instead, so the DCN fetch rides the
request's queue/tokenize/schedule latency rather than its TTFT.

`RoutePrefetcher` is deliberately thin: a bounded background queue in front
of a caller-supplied `prefetch_fn(pod_identifier, block_hashes)` (typically
`EnginePod.prefetch_hashes`, or an RPC to the pod in a real deployment).
Submission never blocks the routing thread — a full queue drops the request
(counted) because a prefetch is a hint, and the engine's fault path remains
correct without it.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Dict, List, Optional

from llm_d_kv_cache_manager_tpu import obs
from llm_d_kv_cache_manager_tpu.metrics import collector as metrics
from llm_d_kv_cache_manager_tpu.utils import logging as kvlog

logger = kvlog.get_logger("kv_connectors.prefetch")

PrefetchFn = Callable[[str, List[int]], int]

# The fixed submitter vocabulary: every plane that rides this queue names
# itself from this set, and the per-source drop metric's label is bounded
# by it (tests/test_metrics_hygiene.py pins the values).
PREFETCH_SOURCES = ("route", "replication", "prediction")


class RoutePrefetcher:
    """Bounded background queue from routing decisions to pod prefetchers."""

    def __init__(self, prefetch_fn: PrefetchFn, queue_bound: int = 64):
        self.prefetch_fn = prefetch_fn
        self._q: "queue.Queue[Optional[tuple]]" = queue.Queue(
            maxsize=max(1, queue_bound)
        )
        self._thread: Optional[threading.Thread] = None
        self._mu = threading.Lock()
        self._closed = False
        self._processed = 0
        self.stats: Dict[str, int] = {
            "submitted": 0, "dropped": 0, "executed": 0, "blocks_queued": 0,
        }
        # Per-source bookkeeping: the queue is shared by route-driven
        # prefetch, hot-prefix replication, and anticipatory prediction,
        # and a drop means something different for each (a route drop
        # costs this request's TTFT; a prediction drop costs nothing now).
        # One aggregate counter hid which plane was being shed.
        self.source_stats: Dict[str, Dict[str, int]] = {}

    def _source(self, source: str) -> Dict[str, int]:
        st = self.source_stats.get(source)
        if st is None:
            st = self.source_stats[source] = {
                "submitted": 0, "dropped": 0, "executed": 0,
                "blocks_queued": 0,
            }
        return st

    def queue_depth(self) -> int:
        """Entries waiting for the worker (approximate, lock-free)."""
        return self._q.qsize()

    def submit(
        self,
        pod_identifier: str,
        block_hashes: List[int],
        source: str = "route",
    ) -> bool:
        """Queue the chosen pod's missing tail for background prefetch.
        Non-blocking: returns False (and counts a drop, per `source`) when
        the queue is full or the prefetcher is closed — the engine's fault
        path stays correct without the hint."""
        if not block_hashes:
            return False
        with self._mu:
            if self._closed:
                return False
            self._ensure_thread()
        try:
            self._q.put_nowait((pod_identifier, list(block_hashes), source))
        except queue.Full:
            self.stats["dropped"] += 1
            self._source(source)["dropped"] += 1
            metrics.count_prefetch_drop(source)
            return False
        self.stats["submitted"] += 1
        self._source(source)["submitted"] += 1
        return True

    def submit_route(self, pod_identifier: str, pod_scores) -> bool:
        """Convenience for the Indexer result: submit exactly the blocks
        the chosen pod misses (`PodScores.missing_tail`)."""
        return self.submit(pod_identifier, pod_scores.missing_tail(pod_identifier))

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, name="kv-route-prefetch", daemon=True
            )
            self._thread.start()

    def _loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            pod_identifier, block_hashes, source = item
            try:
                if not self._closed:
                    # A root trace: the prefetch worker thread never has a
                    # request trace active.
                    with obs.request("transfer.route_prefetch"):
                        n = self.prefetch_fn(pod_identifier, block_hashes)
                    self.stats["executed"] += 1
                    self.stats["blocks_queued"] += int(n or 0)
                    st = self._source(source)
                    st["executed"] += 1
                    st["blocks_queued"] += int(n or 0)
                    metrics.count_route_prefetch(int(n or 0))
            except Exception as e:  # noqa: BLE001 - a hint must never kill
                logger.debug(  # the worker; the engine restores on fault
                    "route prefetch for %s failed: %s", pod_identifier, e
                )
            finally:
                self._processed += 1

    def status(self) -> dict:
        """Introspection snapshot (the /readyz prefetch section): queue
        occupancy plus aggregate AND per-source counters, so a
        budget-bounded prediction drop is distinguishable from a
        route-prefetch drop at a glance."""
        return {
            "queue_depth": self.queue_depth(),
            "queue_bound": self._q.maxsize,
            "stats": dict(self.stats),
            "by_source": {
                src: dict(st) for src, st in self.source_stats.items()
            },
        }

    def drain(self, timeout_s: float = 5.0) -> None:
        """Wait until every submitted entry has been handed to
        `prefetch_fn` (test/bench helper — production callers never wait)."""
        tick = threading.Event()
        waited = 0.0
        while self._processed < self.stats["submitted"] and waited < timeout_s:
            tick.wait(0.01)
            waited += 0.01

    def close(self) -> None:
        with self._mu:
            self._closed = True
            thread = self._thread
        if thread is not None and thread.is_alive():
            self._q.put(None)
            thread.join(timeout=5.0)
        self._thread = None

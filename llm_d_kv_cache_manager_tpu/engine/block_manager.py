"""KV-page manager with prefix caching and KVEvent emission.

The engine-side source of truth the control plane indexes. Responsibilities
(mirroring what vLLM's block manager + KV-event publisher do around the
reference's write plane, /root/reference/pkg/kvcache/kvevents/events.go):

- page allocation for sequences over a fixed HBM page pool,
- prefix caching: full pages are keyed by the *same* chained CBOR+FNV-64a
  hash scheme the control plane recomputes (kvcache/kvblock/hashing.py), so
  an indexer with a matching hash seed maps engine events onto identical
  request keys — the hash-parity invariant, exercised end-to-end in tests,
- copy-on-reuse refcounting: freed sequences leave their pages cached; pages
  are reclaimed LRU on allocation pressure,
- event emission: BlockStored when a full page is committed (with parent
  hash chaining), BlockRemoved when a cached page is reclaimed,
  AllBlocksCleared on reset.

Pure host-side bookkeeping — device work (the actual page tensors) lives in
models/llama.py + ops/paged_attention.py and is driven by engine.EnginePod.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    ChunkedTokenDatabase,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import (
    AllBlocksCleared,
    BlockRemoved,
    BlockStored,
    Event,
    EventBatch,
)
from llm_d_kv_cache_manager_tpu.utils import logging as kvlog

logger = kvlog.get_logger("engine.block_manager")

EventSink = Callable[[EventBatch], None]


@dataclass
class BlockManagerConfig:
    n_pages: int = 512
    page_size: int = 16  # tokens per page == control-plane block size
    hash_seed: str = ""
    enable_prefix_caching: bool = True
    device_tier: Optional[str] = None  # None -> events carry no Medium (default tier)


@dataclass
class SequenceState:
    seq_id: int
    tokens: List[int]
    block_table: List[int]
    num_cached_tokens: int  # prefix-cache hit length at allocation time
    n_hashed_pages: int  # pages already committed (hashed + event emitted)
    lora_id: Optional[int] = None  # adapter scoping for block hashes


class _Page:
    __slots__ = ("page_id", "ref_count", "chunk_hash", "token_ids",
                 "parent_hash", "lora_id")

    def __init__(self, page_id: int):
        self.page_id = page_id
        self.ref_count = 0
        self.chunk_hash: Optional[int] = None  # set when committed (full page)
        # Block provenance, kept so a reclaimed page can be offloaded to the
        # host tier with a well-formed BlockStored (the control plane needs
        # token_ids + parent hash + lora_id to recompute request keys —
        # dropping lora_id would rekey the block into the base keyspace).
        self.token_ids: Optional[List[int]] = None
        self.parent_hash: Optional[int] = None
        self.lora_id: Optional[int] = None


class OutOfPagesError(RuntimeError):
    pass


# Hook signatures for the two-tier data plane (engine/tiering.py):
#  ReclaimHook(chunk_hash, token_ids, parent_hash, page_id, lora_id) — a
#    committed HBM page is about to be dropped; offload it to the host tier
#    if desired.
#  PageLoader(chunk_hash, token_ids, parent_hash, page_id) -> bool — the hash
#    chain missed in HBM; materialize the block into `page_id` from the host
#    store or a remote pod and return True, else False.
ReclaimHook = Callable[[int, List[int], Optional[int], int, Optional[int]], None]
PageLoader = Callable[[int, List[int], Optional[int], int], bool]


class BlockManager:
    def __init__(
        self,
        config: BlockManagerConfig,
        event_sink: Optional[EventSink] = None,
        reclaim_hook: Optional[ReclaimHook] = None,
        page_loader: Optional[PageLoader] = None,
    ):
        self.config = config
        self.event_sink = event_sink
        self.reclaim_hook = reclaim_hook
        self.page_loader = page_loader
        self.token_db = ChunkedTokenDatabase(
            TokenProcessorConfig(block_size=config.page_size, hash_seed=config.hash_seed)
        )
        self._pages = [_Page(i) for i in range(config.n_pages)]
        self._free_fresh = list(range(config.n_pages - 1, -1, -1))  # pop() -> page 0 first
        # hash -> page_id for committed, reusable pages.
        self._hash_to_page: Dict[int, int] = {}
        # LRU of ref_count==0 committed pages, eligible for reclaim.
        self._reclaimable: "OrderedDict[int, None]" = OrderedDict()
        self._seq_counter = 0
        self._sequences: Dict[int, SequenceState] = {}

    # -- stats ---------------------------------------------------------------

    @property
    def num_free_pages(self) -> int:
        return len(self._free_fresh) + len(self._reclaimable)

    @property
    def num_cached_pages(self) -> int:
        return len(self._hash_to_page)

    # -- allocation ----------------------------------------------------------

    def allocate(
        self, tokens: Sequence[int], lora_id: Optional[int] = None
    ) -> SequenceState:
        """Allocate pages for a new sequence, reusing cached prefix pages.

        Returns the sequence state; `num_cached_tokens` tells the caller how
        many leading tokens need no recompute. Raises OutOfPagesError if the
        pool cannot cover the request (caller should retry later). A
        `lora_id` scopes prefix reuse to that adapter's blocks.
        """
        # Genuine Python ints throughout: token ids often arrive as
        # numpy/jax scalars, which the hash fast path and msgpack events
        # both reject.
        tokens = [int(t) for t in tokens]
        n_pages_needed = (len(tokens) + self.config.page_size - 1) // self.config.page_size

        block_table: List[int] = []
        hashes = (
            self.token_db.tokens_to_kv_block_keys(None, tokens, "", lora_id=lora_id)
            if self.config.enable_prefix_caching
            else []
        )
        # 1. Reuse cached pages along the hash chain; on an HBM miss, try the
        # two-tier data plane (host staging store, then remote pods) before
        # giving up on the chain.
        n_cached_pages = 0
        ps = self.config.page_size
        for i, key in enumerate(hashes):
            page_id = self._hash_to_page.get(key.chunk_hash)
            if page_id is None:
                page_id = self._try_load_page(
                    key.chunk_hash,
                    tokens[i * ps:(i + 1) * ps],
                    hashes[i - 1].chunk_hash if i > 0 else None,
                    lora_id,
                )
                if page_id is None:
                    break
            page = self._pages[page_id]
            if page.ref_count == 0:
                self._reclaimable.pop(page_id, None)
            page.ref_count += 1
            block_table.append(page_id)
            n_cached_pages += 1

        # 2. Fresh pages for the rest. Fresh pages are referenced too:
        # without the increment, committing such a page and having another
        # sequence reuse it lets this sequence's free() drop the refcount
        # to zero while the other still holds it — the page becomes
        # reclaimable under a live reader (use-after-reclaim).
        try:
            while len(block_table) < n_pages_needed:
                page_id = self._take_free_page()
                self._pages[page_id].ref_count += 1
                block_table.append(page_id)
        except OutOfPagesError:
            self._rollback(block_table, n_cached_pages)
            raise

        state = SequenceState(
            seq_id=self._seq_counter,
            tokens=tokens,
            block_table=block_table,
            num_cached_tokens=n_cached_pages * self.config.page_size,
            n_hashed_pages=n_cached_pages,
            lora_id=lora_id,
        )
        self._seq_counter += 1
        self._sequences[state.seq_id] = state
        return state

    def commit_prefill(self, state: SequenceState) -> None:
        """Commit the sequence's full pages after prefill compute: hash,
        register for reuse, and emit one BlockStored chaining from the cached
        prefix. Prefill computes KV for every prompt position, so all full
        pages are device-resident and eligible."""
        self._commit_full_pages(state, n_computed=len(state.tokens))

    def append_token(self, state: SequenceState, token: int) -> None:
        """Record one decoded token; allocates a new page at boundaries and
        commits pages as they fill.

        The appended token is *pending*: its KV row is written only by the
        next decode/verify pass that consumes it. A page whose final slot
        holds the pending token is therefore NOT committed here — committing
        it would register (and potentially export, via committed_blocks) a
        page with a garbage KV row that a same-prefix request could attend.
        The engine calls `mark_decode_computed` after the device pass that
        writes the row, which commits the page then."""
        state.tokens.append(int(token))
        pages_needed = (
            len(state.tokens) + self.config.page_size - 1
        ) // self.config.page_size
        self.reserve_pages(state, pages_needed)
        self._commit_full_pages(state, n_computed=len(state.tokens) - 1)

    def mark_decode_computed(self, state: SequenceState) -> None:
        """All of `state.tokens` now have device-resident KV (the decode /
        verify pass that consumed the pending token has written its row).
        Commit any page that completion fills. Callers must only invoke this
        after such a pass; for accounting-only pods it is a harmless
        commit-timing advance."""
        self._commit_full_pages(state, n_computed=len(state.tokens))

    def reserve_pages(self, state: SequenceState, n_total_pages: int) -> None:
        """Extend the sequence's block table with fresh (uncommitted) pages
        so device writes beyond the current token count have somewhere to
        land — speculative decoding writes proposed tokens' KV before
        acceptance, padded prefill writes bucket-tail rows. Unused
        reservations return to the pool on free().

        Atomic: on pool exhaustion the pages grabbed so far are returned
        before raising, so a failed reservation never shrinks the pool for
        other sequences (callers fall back to smaller windows / unpadded
        compute and would otherwise strand the partial grab)."""
        taken: List[int] = []
        try:
            while len(state.block_table) < n_total_pages:
                page_id = self._take_free_page()
                self._pages[page_id].ref_count += 1
                state.block_table.append(page_id)
                taken.append(page_id)
        except OutOfPagesError:
            for page_id in reversed(taken):
                state.block_table.pop()
                self._pages[page_id].ref_count -= 1
                self._free_fresh.append(page_id)
            raise

    def free(self, state: SequenceState) -> None:
        """Release the sequence. Committed pages stay cached (reclaimable);
        uncommitted (partial) pages return to the fresh pool."""
        for i, page_id in enumerate(state.block_table):
            page = self._pages[page_id]
            page.ref_count -= 1
            if page.ref_count > 0:
                continue
            if page.chunk_hash is not None:
                self._reclaimable[page_id] = None
                self._reclaimable.move_to_end(page_id)
            else:
                self._free_fresh.append(page_id)
        self._sequences.pop(state.seq_id, None)

    def clear(self) -> None:
        """Drop everything (engine restart).

        Emits BlockRemoved for every cached page before AllBlocksCleared:
        the event pool (matching the reference, pool.go:332-333) treats
        AllBlocksCleared as a no-op on the assumption that engines emit
        per-block removals — so we must, or the index would keep scoring
        this pod for blocks it no longer holds.
        """
        cached_hashes = list(self._hash_to_page)
        self.__init__(self.config, self.event_sink, self.reclaim_hook,
                      self.page_loader)
        events: List[Event] = []
        if cached_hashes:
            events.append(
                BlockRemoved(block_hashes=cached_hashes, medium=self.config.device_tier)
            )
        events.append(AllBlocksCleared())
        self._emit(events)

    def committed_blocks(self, state: SequenceState):
        """Yield (chunk_hash, token_ids, parent_hash, page_id, lora_id) for
        each committed page of a sequence — the provenance a data plane
        needs to export blocks (engine.EnginePod.export_sequence)."""
        for i in range(state.n_hashed_pages):
            page = self._pages[state.block_table[i]]
            if page.chunk_hash is None or page.token_ids is None:
                continue
            yield (page.chunk_hash, page.token_ids, page.parent_hash,
                   page.page_id, page.lora_id)

    # -- internals -----------------------------------------------------------

    def _try_load_page(
        self,
        chunk_hash: int,
        token_ids: List[int],
        parent_hash: Optional[int],
        lora_id: Optional[int],
    ) -> Optional[int]:
        """On an HBM-chain miss, ask the data plane (engine/tiering.py) to
        materialize the block into a free page. Returns the committed page id
        on success — the page enters the cache exactly as if prefill had
        computed it, including the BlockStored event at the device tier."""
        if self.page_loader is None:
            return None
        try:
            page_id = self._take_free_page()
        except OutOfPagesError:
            return None
        loaded = False
        try:
            loaded = self.page_loader(chunk_hash, token_ids, parent_hash, page_id)
        except Exception as e:  # noqa: BLE001 - a data-plane fault must not
            logger.debug("page loader failed for %x: %s", chunk_hash, e)
            # fail the allocation; the chain just stops here.
        if not loaded:
            self._free_fresh.append(page_id)
            return None
        page = self._pages[page_id]
        page.chunk_hash = chunk_hash
        page.token_ids = list(token_ids)
        page.parent_hash = parent_hash
        page.lora_id = lora_id
        self._hash_to_page[chunk_hash] = page_id
        self._emit([
            BlockStored(
                block_hashes=[chunk_hash],
                parent_block_hash=parent_hash,
                token_ids=list(token_ids),
                block_size=self.config.page_size,
                lora_id=lora_id,
                medium=self.config.device_tier,
            )
        ])
        return page_id

    def _take_free_page(self) -> int:
        if self._free_fresh:
            return self._free_fresh.pop()
        if self._reclaimable:
            page_id, _ = self._reclaimable.popitem(last=False)  # LRU
            page = self._pages[page_id]
            assert page.chunk_hash is not None
            # Only drop the mapping (and tell the control plane) if this page
            # is the registered holder of its hash — a duplicate-content page
            # may have lost the registration race, and its reclaim must not
            # evict the live page's index entry.
            if self._hash_to_page.get(page.chunk_hash) == page_id:
                self._hash_to_page.pop(page.chunk_hash)
                if self.reclaim_hook is not None and page.token_ids is not None:
                    try:
                        self.reclaim_hook(
                            page.chunk_hash, page.token_ids, page.parent_hash,
                            page_id, page.lora_id,
                        )
                    except Exception as e:  # noqa: BLE001 - offload is best-effort
                        logger.debug("reclaim offload failed for %x: %s",
                                     page.chunk_hash, e)
                self._emit([BlockRemoved(block_hashes=[page.chunk_hash],
                                         medium=self.config.device_tier)])
            page.chunk_hash = None
            page.token_ids = None
            page.parent_hash = None
            page.lora_id = None
            return page_id
        raise OutOfPagesError(
            f"no free pages (pool={self.config.n_pages})"
        )

    def _rollback(self, block_table: List[int], n_cached: int) -> None:
        for i, page_id in enumerate(block_table):
            page = self._pages[page_id]
            page.ref_count -= 1
            if i < n_cached:
                if page.ref_count == 0:
                    self._reclaimable[page_id] = None
            else:
                self._free_fresh.append(page_id)

    def _commit_full_pages(self, state: SequenceState, n_computed: int) -> None:
        """Commit pages fully covered by the first `n_computed` tokens —
        the positions whose KV is device-resident. Pages touched by the
        pending (appended-but-not-computed) token stay uncommitted until
        mark_decode_computed."""
        if not self.config.enable_prefix_caching:
            return
        n_full = min(n_computed, len(state.tokens)) // self.config.page_size
        if n_full <= state.n_hashed_pages:
            return

        start_page = state.n_hashed_pages
        parent_hash: Optional[int] = None
        if start_page > 0:
            parent_hash = self._pages[state.block_table[start_page - 1]].chunk_hash

        new_tokens = state.tokens[
            start_page * self.config.page_size : n_full * self.config.page_size
        ]
        parent_key = None
        if parent_hash is not None:
            from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import Key

            parent_key = Key("", parent_hash)
        keys = self.token_db.tokens_to_kv_block_keys(
            parent_key, new_tokens, "", lora_id=state.lora_id
        )

        new_hashes: List[int] = []
        for offset, key in enumerate(keys):
            page = self._pages[state.block_table[start_page + offset]]
            page.chunk_hash = key.chunk_hash
            page.token_ids = new_tokens[
                offset * self.config.page_size:(offset + 1) * self.config.page_size
            ]
            page.parent_hash = parent_hash if offset == 0 else keys[offset - 1].chunk_hash
            page.lora_id = state.lora_id
            # First registration wins: if another page already holds this
            # hash, leave its mapping intact (this page is duplicate content).
            self._hash_to_page.setdefault(key.chunk_hash, page.page_id)
            new_hashes.append(key.chunk_hash)

        state.n_hashed_pages = n_full
        if new_hashes:
            self._emit([
                BlockStored(
                    block_hashes=new_hashes,
                    parent_block_hash=parent_hash,
                    token_ids=new_tokens,
                    block_size=self.config.page_size,
                    lora_id=state.lora_id,
                    medium=self.config.device_tier,
                )
            ])

    def _emit(self, events: List[Event]) -> None:
        if self.event_sink is not None and events:
            self.event_sink(EventBatch(ts=time.time(), events=events))

"""KV-page manager with prefix caching and KVEvent emission.

The engine-side source of truth the control plane indexes. Responsibilities
(mirroring what vLLM's block manager + KV-event publisher do around the
reference's write plane, /root/reference/pkg/kvcache/kvevents/events.go):

- page allocation for sequences over a fixed HBM page pool,
- prefix caching: full pages are keyed by the *same* chained CBOR+FNV-64a
  hash scheme the control plane recomputes (kvcache/kvblock/hashing.py), so
  an indexer with a matching hash seed maps engine events onto identical
  request keys — the hash-parity invariant, exercised end-to-end in tests,
- copy-on-reuse refcounting: freed sequences leave their pages cached; pages
  are reclaimed LRU on allocation pressure,
- event emission: BlockStored when a full page is committed (with parent
  hash chaining), BlockRemoved when a cached page is reclaimed,
  AllBlocksCleared on reset.

Pure host-side bookkeeping — device work (the actual page tensors) lives in
models/llama.py + ops/paged_attention.py and is driven by engine.EnginePod.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    ChunkedTokenDatabase,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import (
    AllBlocksCleared,
    BlockRemoved,
    BlockStored,
    Event,
    EventBatch,
)
from llm_d_kv_cache_manager_tpu.utils import logging as kvlog

logger = kvlog.get_logger("engine.block_manager")

EventSink = Callable[[EventBatch], None]


@dataclass
class BlockManagerConfig:
    n_pages: int = 512
    page_size: int = 16  # tokens per page == control-plane block size
    hash_seed: str = ""
    enable_prefix_caching: bool = True
    device_tier: Optional[str] = None  # None -> events carry no Medium (default tier)


@dataclass
class SequenceState:
    seq_id: int
    tokens: List[int]
    block_table: List[int]
    num_cached_tokens: int  # prefix-cache hit length at allocation time
    n_hashed_pages: int  # pages already committed (hashed + event emitted)
    lora_id: Optional[int] = None  # adapter scoping for block hashes


class _Page:
    __slots__ = ("page_id", "ref_count", "chunk_hash", "token_ids",
                 "parent_hash", "lora_id")

    def __init__(self, page_id: int):
        self.page_id = page_id
        self.ref_count = 0
        self.chunk_hash: Optional[int] = None  # set when committed (full page)
        # Block provenance, kept so a reclaimed page can be offloaded to the
        # host tier with a well-formed BlockStored (the control plane needs
        # token_ids + parent hash + lora_id to recompute request keys —
        # dropping lora_id would rekey the block into the base keyspace).
        self.token_ids: Optional[List[int]] = None
        self.parent_hash: Optional[int] = None
        self.lora_id: Optional[int] = None


class OutOfPagesError(RuntimeError):
    pass


# Hook signatures for the two-tier data plane (engine/tiering.py):
#  ReclaimHook(chunk_hash, token_ids, parent_hash, page_id, lora_id) — a
#    committed HBM page is about to be dropped; offload it to the host tier
#    if desired.
#  PageLoader(chunk_hash, token_ids, parent_hash, page_id) -> bool — the hash
#    chain missed in HBM; materialize the block into `page_id` from the host
#    store or a remote pod and return True, else False.
#  Batched forms (one device dispatch per wave instead of per page):
#  ReclaimManyHook([(hash, token_ids, parent, page_id, lora_id)]) — offload a
#    whole reclaim wave.
#  ChainPlanner([hashes]) -> int — longest restorable prefix, membership
#    checks only (no bytes moved).
#  ChainLoader([(hash, token_ids, parent)], take_pages) -> [page_ids] —
#    fetch a chain prefix's payloads FIRST, then call take_pages(k) for
#    exactly the pages the fetched payloads need, land them in insert
#    dispatches, and return the landed page ids (aligned with the block
#    prefix). take_pages may be called once per landing wave — the
#    pipelined loader (tiering.load_chain) lands long chains in waves so
#    each H2D insert overlaps the next network receive; every call still
#    covers only already-fetched payloads. Fetch-before-take means a stale
#    plan (dead peer, desynced host store) cannot evict LRU-cached HBM
#    pages for a restore that lands nothing.
ReclaimHook = Callable[[int, List[int], Optional[int], int, Optional[int]], None]
PageLoader = Callable[[int, List[int], Optional[int], int], bool]
ReclaimManyHook = Callable[[List[tuple]], None]
ChainPlanner = Callable[[List[int]], int]
ChainLoader = Callable[[List[tuple], Callable[[int], List[int]]], List[int]]


class BlockManager:
    def __init__(
        self,
        config: BlockManagerConfig,
        event_sink: Optional[EventSink] = None,
        reclaim_hook: Optional[ReclaimHook] = None,
        page_loader: Optional[PageLoader] = None,
        reclaim_many_hook: Optional[ReclaimManyHook] = None,
        chain_planner: Optional[ChainPlanner] = None,
        chain_loader: Optional[ChainLoader] = None,
    ):
        self.config = config
        self.event_sink = event_sink
        self.reclaim_hook = reclaim_hook
        self.page_loader = page_loader
        self.reclaim_many_hook = reclaim_many_hook
        self.chain_planner = chain_planner
        self.chain_loader = chain_loader
        self.token_db = ChunkedTokenDatabase(
            TokenProcessorConfig(block_size=config.page_size, hash_seed=config.hash_seed)
        )
        self._pages = [_Page(i) for i in range(config.n_pages)]
        self._free_fresh = list(range(config.n_pages - 1, -1, -1))  # pop() -> page 0 first
        # hash -> page_id for committed, reusable pages.
        self._hash_to_page: Dict[int, int] = {}
        # LRU of ref_count==0 committed pages, eligible for reclaim.
        self._reclaimable: "OrderedDict[int, None]" = OrderedDict()
        self._seq_counter = 0
        self._sequences: Dict[int, SequenceState] = {}

    # -- stats ---------------------------------------------------------------

    @property
    def num_free_pages(self) -> int:
        return len(self._free_fresh) + len(self._reclaimable)

    @property
    def num_cached_pages(self) -> int:
        return len(self._hash_to_page)

    def cached_hashes(self, limit: Optional[int] = None) -> List[int]:
        """Bounded enumeration of device-resident chunk hashes (insertion
        order). The residency-audit re-admit direction: blocks this
        engine holds that the fleet index may have lost."""
        import itertools

        if limit is None:
            return list(self._hash_to_page)
        return list(itertools.islice(self._hash_to_page, max(limit, 0)))

    def is_cached(self, chunk_hash: int) -> bool:
        """True when the block is HBM-resident (committed and reusable)."""
        return chunk_hash in self._hash_to_page

    # -- allocation ----------------------------------------------------------

    def allocate(
        self, tokens: Sequence[int], lora_id: Optional[int] = None
    ) -> SequenceState:
        """Allocate pages for a new sequence, reusing cached prefix pages.

        Returns the sequence state; `num_cached_tokens` tells the caller how
        many leading tokens need no recompute. Raises OutOfPagesError if the
        pool cannot cover the request (caller should retry later). A
        `lora_id` scopes prefix reuse to that adapter's blocks.
        """
        # Genuine Python ints throughout: token ids often arrive as
        # numpy/jax scalars, which the hash fast path and msgpack events
        # both reject.
        tokens = [int(t) for t in tokens]
        n_pages_needed = (len(tokens) + self.config.page_size - 1) // self.config.page_size

        block_table: List[int] = []
        hashes = (
            self.token_db.tokens_to_kv_block_keys(None, tokens, "", lora_id=lora_id)
            if self.config.enable_prefix_caching
            else []
        )
        # 1. Reuse cached pages along the hash chain; on an HBM miss, try the
        # two-tier data plane (host staging store, then remote pods) before
        # giving up on the chain.
        n_cached_pages = 0
        ps = self.config.page_size
        chain_allowed = True
        for i, key in enumerate(hashes):
            page_id = self._hash_to_page.get(key.chunk_hash)
            if page_id is None and chain_allowed:
                # The data plane restores the longest restorable prefix of
                # the remaining chain in ONE batch; restored blocks register
                # in _hash_to_page, so re-checking picks them up in order.
                # A restore's own reclaims can offload LATER chain blocks to
                # the host tier (making them restorable one step behind), so
                # keep retrying as long as each attempt makes progress —
                # bounded by the chain length.
                chain_allowed = self._try_load_chain(hashes, tokens, i, lora_id) > 0
                page_id = self._hash_to_page.get(key.chunk_hash)
            if page_id is None:
                break
            page = self._pages[page_id]
            if page.ref_count == 0:
                self._reclaimable.pop(page_id, None)
            page.ref_count += 1
            block_table.append(page_id)
            n_cached_pages += 1

        # 2. Fresh pages for the rest. Fresh pages are referenced too:
        # without the increment, committing such a page and having another
        # sequence reuse it lets this sequence's free() drop the refcount
        # to zero while the other still holds it — the page becomes
        # reclaimable under a live reader (use-after-reclaim).
        try:
            for page_id in self._take_free_pages(
                n_pages_needed - len(block_table)
            ):
                self._pages[page_id].ref_count += 1
                block_table.append(page_id)
        except OutOfPagesError:
            self._rollback(block_table, n_cached_pages)
            raise

        state = SequenceState(
            seq_id=self._seq_counter,
            tokens=tokens,
            block_table=block_table,
            num_cached_tokens=n_cached_pages * self.config.page_size,
            n_hashed_pages=n_cached_pages,
            lora_id=lora_id,
        )
        self._seq_counter += 1
        self._sequences[state.seq_id] = state
        return state

    def commit_prefill(self, state: SequenceState) -> None:
        """Commit the sequence's full pages after prefill compute: hash,
        register for reuse, and emit one BlockStored chaining from the cached
        prefix. Prefill computes KV for every prompt position, so all full
        pages are device-resident and eligible."""
        self._commit_full_pages(state, n_computed=len(state.tokens))

    def append_token(self, state: SequenceState, token: int) -> None:
        """Record one decoded token; allocates a new page at boundaries and
        commits pages as they fill.

        The appended token is *pending*: its KV row is written only by the
        next decode/verify pass that consumes it. A page whose final slot
        holds the pending token is therefore NOT committed here — committing
        it would register (and potentially export, via committed_blocks) a
        page with a garbage KV row that a same-prefix request could attend.
        The engine calls `mark_decode_computed` after the device pass that
        writes the row, which commits the page then."""
        state.tokens.append(int(token))
        pages_needed = (
            len(state.tokens) + self.config.page_size - 1
        ) // self.config.page_size
        self.reserve_pages(state, pages_needed)
        self._commit_full_pages(state, n_computed=len(state.tokens) - 1)

    def mark_decode_computed(self, state: SequenceState) -> None:
        """All of `state.tokens` now have device-resident KV (the decode /
        verify pass that consumed the pending token has written its row).
        Commit any page that completion fills. Callers must only invoke this
        after such a pass; for accounting-only pods it is a harmless
        commit-timing advance."""
        self._commit_full_pages(state, n_computed=len(state.tokens))

    def reserve_pages(self, state: SequenceState, n_total_pages: int) -> None:
        """Extend the sequence's block table with fresh (uncommitted) pages
        so device writes beyond the current token count have somewhere to
        land — speculative decoding writes proposed tokens' KV before
        acceptance, padded prefill writes bucket-tail rows. Unused
        reservations return to the pool on free().

        Atomic: on pool exhaustion nothing is taken (the bulk grab is
        all-or-nothing), so a failed reservation never shrinks the pool for
        other sequences (callers fall back to smaller windows / unpadded
        compute and would otherwise strand the partial grab)."""
        for page_id in self._take_free_pages(
            n_total_pages - len(state.block_table)
        ):
            self._pages[page_id].ref_count += 1
            state.block_table.append(page_id)

    def free(self, state: SequenceState) -> None:
        """Release the sequence. Committed pages stay cached (reclaimable);
        uncommitted (partial) pages return to the fresh pool."""
        for i, page_id in enumerate(state.block_table):
            page = self._pages[page_id]
            page.ref_count -= 1
            if page.ref_count > 0:
                continue
            if page.chunk_hash is not None:
                self._reclaimable[page_id] = None
                self._reclaimable.move_to_end(page_id)
            else:
                self._free_fresh.append(page_id)
        self._sequences.pop(state.seq_id, None)

    def clear(self) -> None:
        """Drop everything (engine restart).

        Emits BlockRemoved for every cached page before AllBlocksCleared:
        the event pool (matching the reference, pool.go:332-333) treats
        AllBlocksCleared as a no-op on the assumption that engines emit
        per-block removals — so we must, or the index would keep scoring
        this pod for blocks it no longer holds.
        """
        cached_hashes = list(self._hash_to_page)
        self.__init__(self.config, self.event_sink, self.reclaim_hook,
                      self.page_loader, self.reclaim_many_hook,
                      self.chain_planner, self.chain_loader)
        events: List[Event] = []
        if cached_hashes:
            events.append(
                BlockRemoved(block_hashes=cached_hashes, medium=self.config.device_tier)
            )
        events.append(AllBlocksCleared())
        self._emit(events)

    def committed_blocks(self, state: SequenceState):
        """Yield (chunk_hash, token_ids, parent_hash, page_id, lora_id) for
        each committed page of a sequence — the provenance a data plane
        needs to export blocks (engine.EnginePod.export_sequence)."""
        for i in range(state.n_hashed_pages):
            page = self._pages[state.block_table[i]]
            if page.chunk_hash is None or page.token_ids is None:
                continue
            yield (page.chunk_hash, page.token_ids, page.parent_hash,
                   page.page_id, page.lora_id)

    # -- internals -----------------------------------------------------------

    def _try_load_chain(
        self,
        hashes: List,
        tokens: List[int],
        start: int,
        lora_id: Optional[int],
    ) -> int:
        """On an HBM miss, materialize the longest restorable prefix of the
        remaining hash chain from the data plane in ONE batch: plan
        (membership checks), fetch the payloads, take exactly the pages the
        fetched payloads need, land them in a single device dispatch
        (tiering.load_chain), and commit with one chained multi-block
        BlockStored — the shape vLLM itself emits for a stored chain.
        Restored blocks register in _hash_to_page; the allocate loop
        re-checks and consumes them. Returns the number of blocks landed."""
        if self.chain_loader is None and self.page_loader is None:
            return 0
        ps = self.config.page_size
        rest = hashes[start:]
        # Truncate the batch at the first duplicate hash (both occurrences
        # registering would strand a page) and at the first HBM-resident
        # hash (re-fetching it would clobber the live page's registration —
        # the outer loop consumes it from HBM). Later occurrences hit
        # _hash_to_page on the outer loop's re-check.
        seen = set()
        uniq = []
        for key in rest:
            if key.chunk_hash in seen or key.chunk_hash in self._hash_to_page:
                break
            seen.add(key.chunk_hash)
            uniq.append(key)
        if not uniq:
            return 0
        if self.chain_planner is not None:
            n_plan = min(
                self.chain_planner([k.chunk_hash for k in uniq]), len(uniq)
            )
        elif self.chain_loader is not None:
            n_plan = len(uniq)
        else:
            n_plan = 1  # legacy single-page loader probes one block
        if n_plan <= 0:
            return 0
        blocks = []
        for j in range(n_plan):
            i = start + j
            blocks.append((
                uniq[j].chunk_hash,
                tokens[i * ps:(i + 1) * ps],
                hashes[i - 1].chunk_hash if i > 0 else None,
            ))

        landed_pages: List[int] = []
        taken_log: List[int] = []
        if self.chain_loader is not None:
            def take_pages(k: int) -> List[int]:
                got = self._take_free_pages(min(k, self.num_free_pages))
                taken_log.extend(got)
                return got

            try:
                landed_pages = list(self.chain_loader(blocks, take_pages))
            except Exception as e:  # noqa: BLE001 - a data-plane fault must
                logger.debug("chain loader failed: %s", e)  # not fail allocate
                landed_pages = []
        else:
            for chunk_hash, token_ids, parent_hash in blocks:
                if self.num_free_pages <= 0:
                    break
                page_id = self._take_free_pages(1)[0]
                taken_log.append(page_id)
                try:
                    ok = self.page_loader(
                        chunk_hash, token_ids, parent_hash, page_id
                    )
                except Exception as e:  # noqa: BLE001
                    logger.debug("page loader failed for %x: %s",
                                 chunk_hash, e)
                    ok = False
                if not ok:
                    break
                landed_pages.append(page_id)

        n_loaded = len(landed_pages)
        stored_hashes: List[int] = []
        stored_tokens: List[int] = []
        for j in range(n_loaded):
            chunk_hash, token_ids, parent_hash = blocks[j]
            page = self._pages[landed_pages[j]]
            page.chunk_hash = chunk_hash
            page.token_ids = list(token_ids)
            page.parent_hash = parent_hash
            page.lora_id = lora_id
            self._hash_to_page[chunk_hash] = page_id = landed_pages[j]
            stored_hashes.append(chunk_hash)
            stored_tokens.extend(token_ids)
        # Anything taken but not landed (loader fault, short fetch) goes
        # straight back to the pool.
        landed_set = set(landed_pages)
        for page_id in taken_log:
            if page_id not in landed_set:
                self._free_fresh.append(page_id)
        if stored_hashes:
            self._emit([
                BlockStored(
                    block_hashes=stored_hashes,
                    parent_block_hash=blocks[0][2],
                    token_ids=stored_tokens,
                    block_size=ps,
                    lora_id=lora_id,
                    medium=self.config.device_tier,
                )
            ])
        return n_loaded

    def _take_free_page(self) -> int:
        return self._take_free_pages(1)[0]

    def _take_free_pages(self, k: int) -> List[int]:
        """k pages in one grab, fresh pool first then LRU reclaim. Atomic:
        on shortfall nothing is taken. The whole reclaim wave offloads in
        ONE batched hook call (one device extract dispatch) and drops with
        ONE multi-hash BlockRemoved — per-page hooks/events made a K-page
        admission pay K device round trips and K wire events."""
        if k <= 0:
            return []
        got = [
            self._free_fresh.pop()
            for _ in range(min(k, len(self._free_fresh)))
        ]
        need = k - len(got)
        if need == 0:
            return got
        if len(self._reclaimable) < need:
            self._free_fresh.extend(reversed(got))
            raise OutOfPagesError(
                f"no free pages (pool={self.config.n_pages})"
            )
        victims = [
            self._reclaimable.popitem(last=False)[0] for _ in range(need)
        ]  # LRU order
        offload_blocks: List[tuple] = []
        removed_hashes: List[int] = []
        for page_id in victims:
            page = self._pages[page_id]
            assert page.chunk_hash is not None
            # Only drop the mapping (and tell the control plane) if this
            # page is the registered holder of its hash — a duplicate-content
            # page may have lost the registration race, and its reclaim must
            # not evict the live page's index entry.
            if self._hash_to_page.get(page.chunk_hash) == page_id:
                self._hash_to_page.pop(page.chunk_hash)
                if page.token_ids is not None:
                    offload_blocks.append((
                        page.chunk_hash, page.token_ids, page.parent_hash,
                        page_id, page.lora_id,
                    ))
                removed_hashes.append(page.chunk_hash)
            page.chunk_hash = None
            page.token_ids = None
            page.parent_hash = None
            page.lora_id = None
        if offload_blocks:
            if self.reclaim_many_hook is not None:
                try:
                    self.reclaim_many_hook(offload_blocks)
                except Exception as e:  # noqa: BLE001 - offload is best-effort
                    logger.debug("reclaim offload failed: %s", e)
            elif self.reclaim_hook is not None:
                # Per-block isolation: one failing offload must not drop
                # the rest of the wave from both tiers.
                for block in offload_blocks:
                    try:
                        self.reclaim_hook(*block)
                    except Exception as e:  # noqa: BLE001
                        logger.debug("reclaim offload failed for %x: %s",
                                     block[0], e)
        if removed_hashes:
            self._emit([BlockRemoved(block_hashes=removed_hashes,
                                     medium=self.config.device_tier)])
        return got + victims

    def _rollback(self, block_table: List[int], n_cached: int) -> None:
        for i, page_id in enumerate(block_table):
            page = self._pages[page_id]
            page.ref_count -= 1
            if i < n_cached:
                if page.ref_count == 0:
                    self._reclaimable[page_id] = None
            else:
                self._free_fresh.append(page_id)

    def _commit_full_pages(self, state: SequenceState, n_computed: int) -> None:
        """Commit pages fully covered by the first `n_computed` tokens —
        the positions whose KV is device-resident. Pages touched by the
        pending (appended-but-not-computed) token stay uncommitted until
        mark_decode_computed."""
        if not self.config.enable_prefix_caching:
            return
        n_full = min(n_computed, len(state.tokens)) // self.config.page_size
        if n_full <= state.n_hashed_pages:
            return

        start_page = state.n_hashed_pages
        parent_hash: Optional[int] = None
        if start_page > 0:
            parent_hash = self._pages[state.block_table[start_page - 1]].chunk_hash

        new_tokens = state.tokens[
            start_page * self.config.page_size : n_full * self.config.page_size
        ]
        parent_key = None
        if parent_hash is not None:
            from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import Key

            parent_key = Key("", parent_hash)
        keys = self.token_db.tokens_to_kv_block_keys(
            parent_key, new_tokens, "", lora_id=state.lora_id
        )

        new_hashes: List[int] = []
        for offset, key in enumerate(keys):
            page = self._pages[state.block_table[start_page + offset]]
            page.chunk_hash = key.chunk_hash
            page.token_ids = new_tokens[
                offset * self.config.page_size:(offset + 1) * self.config.page_size
            ]
            page.parent_hash = parent_hash if offset == 0 else keys[offset - 1].chunk_hash
            page.lora_id = state.lora_id
            # First registration wins: if another page already holds this
            # hash, leave its mapping intact (this page is duplicate content).
            self._hash_to_page.setdefault(key.chunk_hash, page.page_id)
            new_hashes.append(key.chunk_hash)

        state.n_hashed_pages = n_full
        if new_hashes:
            self._emit([
                BlockStored(
                    block_hashes=new_hashes,
                    parent_block_hash=parent_hash,
                    token_ids=new_tokens,
                    block_size=self.config.page_size,
                    lora_id=state.lora_id,
                    medium=self.config.device_tier,
                )
            ])

    def _emit(self, events: List[Event]) -> None:
        if self.event_sink is not None and events:
            self.event_sink(EventBatch(ts=time.time(), events=events))

from llm_d_kv_cache_manager_tpu.engine.block_manager import (
    BlockManager,
    BlockManagerConfig,
)
from llm_d_kv_cache_manager_tpu.engine.engine import EnginePod, EnginePodConfig
from llm_d_kv_cache_manager_tpu.engine.scheduler import Request, Scheduler

__all__ = [
    "BlockManager",
    "BlockManagerConfig",
    "EnginePod",
    "EnginePodConfig",
    "Request",
    "Scheduler",
]

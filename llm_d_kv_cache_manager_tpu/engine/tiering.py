"""Two-tier KV policy: wires kv_connectors into the serving loop.

The reference plans this behavior but never implements it (its
kv_connectors/ directory is empty; its device tiers "gpu"/"cpu" exist only
as scoring weights). Here the tiers are real:

- **reclaim → offload**: when the block manager reclaims a committed HBM
  page under allocation pressure, the page's bytes are staged in the host
  store (C++ transfer server) instead of vanishing — BlockRemoved(hbm) +
  BlockStored(host) flow to the control plane, so the scorer keeps ranking
  this pod for the block at the host-tier weight.
- **miss → restore/onboard**: when an allocation's hash chain misses in
  HBM, the block is materialized from the host store, or — if a peer
  resolver is configured — fetched from another pod's transfer server over
  DCN, landing as a normal committed page (device-tier BlockStored). Pod B
  can thereby serve a prefix it never computed.
- **export**: explicit staging of a live sequence's committed pages
  (prefill/decode disaggregation push): the pages stay in HBM, a copy
  becomes fetchable by peers.

The page payload is opaque bytes; `PageCodec` implementations serialize one
logical page across all layers (bf16 pair or int8 quantized 4-tuple).
Accounting-only pods use `NullPageCodec` — the full event/scoring behavior
without device bytes, which is what the fleet bench simulates.
"""

from __future__ import annotations

import queue
import threading
from collections import OrderedDict
from typing import TYPE_CHECKING, Callable, Dict, List, Mapping, Optional, Tuple

from llm_d_kv_cache_manager_tpu import obs
from llm_d_kv_cache_manager_tpu.engine.costs import (
    PEER,
    READY,
    STAGED,
    TransferCostModel,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import (
    Key,
    base_pod_identifier,
)
from llm_d_kv_cache_manager_tpu.metrics import collector as metrics
from llm_d_kv_cache_manager_tpu.utils import logging as kvlog

if TYPE_CHECKING:  # kv_connectors loads the ctypes lib; keep it optional at
    from llm_d_kv_cache_manager_tpu.kv_connectors.connector import (  # runtime
        KVConnector,
    )

logger = kvlog.get_logger("engine.tiering")

# (host, port) of a peer pod's transfer server, or None.
PeerResolver = Callable[[int], Optional[Tuple[str, int]]]


class PageCodec:
    """Serializes logical KV pages (all layers) to/from opaque bytes.

    The batch forms are the device-crossing unit: a real codec moves N
    pages in one dispatch (engine._DevicePageCodec), so chain restores and
    bulk reclaims pay O(1) round trips instead of O(pages). The single-page
    forms default to the N=1 batch."""

    page_nbytes: int = 0

    def extract(self, page_id: int) -> bytes:
        return self.extract_many([page_id])[0]

    def extract_many_async(self, page_ids):
        """Capture the pages' CURRENT content and return a zero-arg resolve
        callable producing the payload bytes. The base implementation
        captures by extracting eagerly; device codecs override to enqueue
        the gather + async host copy immediately (a snapshot — later
        overwrites of the pages cannot corrupt it) and pay only the
        already-overlapped host sync at resolve time."""
        payloads = self.extract_many(list(page_ids))
        return lambda: payloads

    def insert(self, page_id: int, payload: bytes) -> None:
        self.insert_many([(page_id, payload)])

    def extract_many(self, page_ids) -> List[bytes]:
        raise NotImplementedError

    def insert_many(self, items) -> None:
        raise NotImplementedError


class NullPageCodec(PageCodec):
    """Accounting-only pods: zero-byte payloads, full event behavior."""

    def extract_many(self, page_ids) -> List[bytes]:
        return [b"" for _ in page_ids]

    def insert_many(self, items) -> None:
        for _, payload in items:
            if payload:
                raise ValueError(
                    "accounting-only pod received a non-empty block"
                )


class TieredKVStore:
    """Per-pod two-tier policy over a KVConnector.

    Bounded host store: staging beyond `capacity_blocks` drops the
    least-recently-staged block first (BlockRemoved(host) via the
    connector), so host RAM use is capped like any cache tier.
    """

    def __init__(
        self,
        connector: "KVConnector",
        codec: PageCodec,
        capacity_blocks: int = 1024,
        peer_resolver: Optional[PeerResolver] = None,
        cost_model: Optional[TransferCostModel] = None,
        prefetch_capacity_blocks: int = 64,
        async_stage_capacity_pages: int = 128,
        stage_wave_pages: int = 16,
        onboard_wave_blocks: int = 8,
        fetch_batch_blocks: int = 32,
    ):
        self.connector = connector
        self.codec = codec
        self.capacity_blocks = capacity_blocks
        self.peer_resolver = peer_resolver
        # Transfer-plane pipelining bounds: pages per extract wave in the
        # double-buffered stager (_stage_many), blocks per H2D insert wave
        # in load_chain (each wave's scatter overlaps the next network
        # receive), and blocks per multi-block DCN round trip.
        self.stage_wave_pages = max(1, stage_wave_pages)
        self.onboard_wave_blocks = max(1, onboard_wave_blocks)
        self.fetch_batch_blocks = max(1, fetch_batch_blocks)
        # Transfer-vs-recompute gate (engine/costs.py). None admits every
        # restorable block — the pre-gate behavior, which is right for
        # accounting-only pods (zero payload bytes) and mechanics tests;
        # EnginePod passes a model-seeded gate for real pods.
        self.cost_model = cost_model
        # hash -> None, insertion-ordered: the host store's eviction queue.
        self._staged: "OrderedDict[int, None]" = OrderedDict()
        # hash -> (payload, source): payloads the async prefetcher already
        # pulled into host RAM; load_chain lands them at insert-only cost.
        self._ready: "OrderedDict[int, Tuple[bytes, str]]" = OrderedDict()
        self._ready_cap = max(0, prefetch_capacity_blocks)
        # Eager staging (stage_async): hash -> in-flight snapshot entry.
        # Bounded by _async_stage_cap pages of un-resolved snapshots so
        # pending gather outputs cannot hold HBM without limit.
        self._pending_stage: Dict[int, dict] = {}
        self._pending_pages = 0
        self._async_stage_cap = max(0, async_stage_capacity_pages)
        self._stage_q: "queue.Queue[Optional[dict]]" = queue.Queue()
        self._stage_thread: Optional[threading.Thread] = None
        self._mu = threading.Lock()  # guards _staged and _ready
        self._prefetch_q: "queue.Queue[Optional[List[int]]]" = queue.Queue()
        self._prefetch_thread: Optional[threading.Thread] = None
        self._inflight: set = set()  # hashes queued/being fetched
        self._closed = False
        self.stats: Dict[str, int] = {
            "offloads": 0, "restores": 0, "onboards": 0, "host_evictions": 0,
            "gated_blocks": 0, "prefetched": 0, "ready_hits": 0,
            "stage_waves": 0, "batched_fetches": 0,
        }

    # -- BlockManager hook: reclaim → offload ------------------------------

    def reclaim_hook(
        self, chunk_hash: int, token_ids: List[int],
        parent_hash: Optional[int], page_id: int,
        lora_id: Optional[int] = None,
    ) -> None:
        self.reclaim_many_hook(
            [(chunk_hash, token_ids, parent_hash, page_id, lora_id)]
        )

    def reclaim_many_hook(self, blocks: List[tuple]) -> None:
        """Batched reclaim→offload: one device extract dispatch for the
        whole reclaim wave. `blocks`: (hash, token_ids, parent, page_id,
        lora_id) tuples. Only blocks actually host-resident afterwards
        count as offloads — a failed stage is not an offload."""
        self.stats["offloads"] += self._stage_many(blocks)

    # -- P/D disaggregation: stage without reclaiming ----------------------

    def export_block(
        self, chunk_hash: int, token_ids: List[int],
        parent_hash: Optional[int], page_id: int,
        lora_id: Optional[int] = None,
    ) -> None:
        self._stage_many(
            [(chunk_hash, token_ids, parent_hash, page_id, lora_id)]
        )

    def export_blocks(self, blocks: List[tuple]) -> None:
        """Stage a sequence's committed pages in one extract dispatch
        (engine.export_sequence — the P/D disaggregation push)."""
        self._stage_many(blocks)

    # -- BlockManager hook: miss → restore/onboard -------------------------

    def page_loader(
        self, chunk_hash: int, token_ids: List[int],
        parent_hash: Optional[int], page_id: int,
    ) -> bool:
        landed = self.load_chain(
            [(chunk_hash, token_ids, parent_hash)], lambda k: [page_id]
        )
        return len(landed) == 1

    def plan_restore(self, chunk_hashes: List[int]) -> int:
        """Longest prefix of `chunk_hashes` WORTH materializing: membership
        checks (prefetched payloads, local host store, then peer index —
        no bytes moved), truncated by the transfer-vs-recompute gate. The
        block manager calls this before grabbing pages so a chain restore
        allocates exactly what will land."""
        sources: List[str] = []
        for h in chunk_hashes:
            source = self._source_of(h)
            if source is None:
                break
            sources.append(source)
        if not sources:
            return 0
        if self.cost_model is None:
            return len(sources)
        # page_size scales cost and savings identically, so 1 suffices.
        admitted = self.cost_model.admit_prefix(sources, 1)
        self.stats["gated_blocks"] += len(sources) - admitted
        return admitted

    def _live_fetch_admissible(self, so_far: List[str], source: str) -> bool:
        """Cumulative gate re-check for a critical-path fetch: admit block
        len(so_far) at `source` cost only if the whole chain so far plus
        it stays admissible — the same arithmetic plan_restore ran, at the
        costs actually being paid."""
        if self.cost_model is None:
            return True
        return self.cost_model.admit_prefix(so_far + [source], 1) == len(so_far) + 1

    def _source_of(self, chunk_hash: int) -> Optional[str]:
        """Cheapest available source for a block, or None when absent
        everywhere (READY beats STAGED beats PEER — same order load_chain
        fetches)."""
        with self._mu:
            if chunk_hash in self._ready:
                return READY
            if chunk_hash in self._staged:
                return STAGED
        if self.peer_resolver is not None and self.peer_resolver(chunk_hash) is not None:
            return PEER
        return None

    def load_chain(self, blocks: List[tuple], take_pages) -> List[int]:
        """Materialize a chain prefix, pipelined: payloads are fetched in
        chain order (prefetched ready buffer, then local host store, then
        peers over DCN — consecutive same-peer blocks ride ONE multi-block
        round trip instead of one per block) and land in waves of
        `onboard_wave_blocks`: each wave calls `take_pages(k)` for exactly
        the pages its fetched payloads need and dispatches one insert. The
        jitted scatter is asynchronous, so a wave's H2D onboard overlaps
        the next wave's network receive. `blocks`: (chunk_hash, token_ids,
        parent_hash) in chain order. Returns the landed page ids (aligned
        with the block prefix) — fetches stop at the first miss so the
        hash chain never gets a hole, and fetch-before-take means a stale
        plan cannot evict HBM-cached pages for a restore that lands
        nothing.

        A root trace in the flight recorder (`transfer.load_chain`; a
        nested stage when the caller is already traced), with the
        staged/peer fetches and onboard waves as child spans."""
        with obs.request("transfer.load_chain", {"blocks": len(blocks)}):
            return self._load_chain_impl(blocks, take_pages)

    def _load_chain_impl(self, blocks: List[tuple], take_pages) -> List[int]:
        landed: List[int] = []
        buffer: List[tuple] = []  # fetched, not yet landed: (payload, stat)
        cost_sources: List[str] = []  # what each fetched block actually cost
        max_size = max(self.codec.page_nbytes, 1)
        wave = self.onboard_wave_blocks
        exhausted = False

        def land_wave() -> None:
            """Take pages for the buffered payloads and dispatch ONE insert.
            A short take (pool exhausted) lands what fits and stops the
            chain — nothing more could land anyway."""
            nonlocal buffer, exhausted
            if not buffer or exhausted:
                buffer = []
                return
            with obs.stage("transfer.onboard_wave"):
                page_ids = take_pages(len(buffer))
                use = buffer[: len(page_ids)]
                if use:
                    self.codec.insert_many(
                        [(pid, p) for pid, (p, _) in zip(page_ids, use)]
                    )
                    for _, stat in use:
                        self.stats[stat] += 1
                    landed.extend(page_ids[: len(use)])
            if len(use) < len(buffer):
                exhausted = True
            buffer = []

        i = 0
        n = len(blocks)
        while i < n and not exhausted:
            chunk_hash = blocks[i][0]
            payload = None
            stat = None
            with self._mu:
                ready = self._ready.pop(chunk_hash, None)
                staged = chunk_hash in self._staged
            if ready is not None:
                # Prefetched: the fetch already happened off the critical
                # path; classify by where the prefetcher got it so the
                # restore/onboard stats stay truthful.
                payload, stat = ready[0], (
                    "restores" if ready[1] == STAGED else "onboards"
                )
                cost_sources.append(READY)
                self.stats["ready_hits"] += 1
            if payload is None and staged:
                # plan_restore may have admitted this block at READY cost
                # and the ready entry got evicted since (prefetcher cap
                # churn): re-check the gate at the cost actually paid, so
                # a transfer the economics refuse cannot sneak onto the
                # critical path through that race.
                if not self._live_fetch_admissible(cost_sources, STAGED):
                    break
                payload = self.connector.fetch_staged(chunk_hash, max_size)
                if payload is not None:
                    stat = "restores"
                    cost_sources.append(STAGED)
            if payload is not None:
                buffer.append((payload, stat))
                i += 1
                if len(buffer) >= wave:
                    land_wave()
                continue

            # Peer (DCN) leg. Batch the run of consecutive chain blocks
            # that miss the local tiers and resolve to the SAME peer into
            # one multi-block round trip — the serial protocol paid one
            # RTT per block per chain. When the index shows additional
            # holders for the run's head, they ride along as hedge/
            # fallback targets (first valid reply wins; see
            # _fetch_peer_many).
            if self.peer_resolver is None:
                break
            addr = self.peer_resolver(chunk_hash)
            if addr is None:
                break
            candidates = self._peer_candidates(chunk_hash, addr)
            run = [chunk_hash]
            j = i + 1
            while j < n and len(run) < self.fetch_batch_blocks:
                h = blocks[j][0]
                with self._mu:
                    local = h in self._ready or h in self._staged
                if local or self.peer_resolver(h) != addr:
                    break
                run.append(h)
                j += 1
            if self.cost_model is not None:
                # Same cumulative arithmetic as the per-block gate, applied
                # to the whole run at once: admit only the prefix the
                # economics accept at PEER cost.
                admitted = self.cost_model.admit_prefix(
                    cost_sources + [PEER] * len(run), 1
                ) - len(cost_sources)
                if admitted <= 0:
                    break
                run = run[:admitted]
            payloads = self._fetch_peer_many(
                addr, run, max_size, candidates=candidates
            )
            miss = False
            for payload in payloads:
                if payload is None:
                    miss = True
                    break
                buffer.append((payload, "onboards"))
                cost_sources.append(PEER)
                i += 1
                if len(buffer) >= wave and not exhausted:
                    land_wave()
            if miss:
                break
        land_wave()
        return landed

    def _peer_candidates(
        self, chunk_hash: int, primary: Tuple[str, int]
    ) -> List[Tuple[str, int]]:
        """Holder list for a hedged fetch: the resolver's primary pick
        first (bit-identical healthy-path behavior), then the remaining
        holders in the resolver's rendezvous ranking. Resolvers without a
        `candidates` form (fakes, plain callables) yield just the
        primary — no hedging."""
        candidates_fn = getattr(self.peer_resolver, "candidates", None)
        if candidates_fn is None:
            return [primary]
        try:
            ranked = candidates_fn(chunk_hash)
        except Exception:  # noqa: BLE001 - hedging is an optimization
            return [primary]
        out = [primary]
        for addr in ranked:
            if addr != primary:
                out.append(addr)
        return out

    def _fetch_peer_many(
        self,
        addr: Tuple[str, int],
        hashes: List[int],
        max_size: int,
        candidates: Optional[List[Tuple[str, int]]] = None,
    ) -> List[Optional[bytes]]:
        """One multi-block DCN round trip when the connector supports it
        (KVConnector.onboard_payloads); per-block fetches otherwise (fake
        connectors in tests, stale .so builds). With >= 2 candidate
        holders and a hedging-capable connector, the fetch is hedged: the
        primary gets an adaptive latency budget, then the next
        rendezvous-ranked holder is raced — the first valid reply wins,
        so a slow/corrupt/broken peer costs the hedge delay instead of
        the full timeout ladder."""
        with obs.stage("transfer.peer_fetch"):
            if candidates is not None and len(candidates) > 1:
                hedged = getattr(
                    self.connector, "onboard_payloads_hedged", None
                )
                if hedged is not None:
                    self.stats["batched_fetches"] += 1
                    return hedged(candidates, hashes, max_size)
            batched = getattr(self.connector, "onboard_payloads", None)
            if batched is not None and len(hashes) > 1:
                self.stats["batched_fetches"] += 1
                return batched(addr[0], addr[1], hashes, max_size)
            out: List[Optional[bytes]] = []
            for h in hashes:
                payload = self.connector.onboard_payload(
                    addr[0], addr[1], h, max_size
                )
                out.append(payload)
                if payload is None:
                    break  # chain cut: later blocks can't land anyway
            return out

    def _fetch_staged_many(
        self, hashes: List[int], max_size: int,
    ) -> List[Optional[bytes]]:
        with obs.stage("transfer.staged_fetch"):
            batched = getattr(self.connector, "fetch_staged_many", None)
            if batched is not None and len(hashes) > 1:
                return batched(hashes, max_size)
            return [self.connector.fetch_staged(h, max_size) for h in hashes]

    # -- async prefetch ----------------------------------------------------

    def prefetch(self, chunk_hashes: List[int]) -> int:
        """Queue block payload fetches on the background prefetcher. The
        network/loopback fetch happens off the serving thread; the device
        insert still happens at allocate time, from the ready buffer, at
        insert-only cost. Returns how many fetches were queued.

        Gate: a prefetched block lands at insert-only cost, so prefetch
        only when even that cost beats recompute (insert cost is uniform
        per block, so the single-block check is exact for whole chains)."""
        if self._ready_cap <= 0 or self._closed:
            return 0
        if self.cost_model is not None and self.cost_model.admit_prefix(
            [READY], 1
        ) == 0:
            return 0
        # Membership-filter BEFORE charging the ready-cap budget: a submit
        # can carry dozens of hashes that exist nowhere, and each would
        # otherwise consume a budget slot (displacing genuinely restorable
        # blocks from this submit) just to be discarded by the background
        # fetch. _source_of is membership-only — no bytes move.
        candidates = [h for h in chunk_hashes if self._source_of(h) is not None]
        if not candidates:
            return 0
        todo: List[int] = []
        with self._mu:
            # Never fetch past the ready-buffer cap: chains restore
            # head-first, so fetching a long tail would evict the head —
            # the part load_chain consumes first — and the evicted
            # payloads' fetch traffic would be pure waste.
            budget = self._ready_cap - len(self._ready) - len(self._inflight)
            for h in candidates:
                if budget <= 0:
                    break
                if h in self._ready or h in self._inflight:
                    continue
                self._inflight.add(h)
                todo.append(h)
                budget -= 1
        if not todo:
            return 0
        self._ensure_prefetcher()
        self._prefetch_q.put(todo)
        return len(todo)

    def _ensure_prefetcher(self) -> None:
        if self._prefetch_thread is None or not self._prefetch_thread.is_alive():
            self._prefetch_thread = threading.Thread(
                target=self._prefetch_loop, name="kv-tier-prefetch", daemon=True
            )
            self._prefetch_thread.start()

    def _prefetch_loop(self) -> None:
        while True:
            batch = self._prefetch_q.get()
            if batch is None:
                return
            try:
                # On close, drain without fetching: pending batches must
                # not hold the connector open through slow-peer timeouts
                # after the pod is being torn down.
                if not self._closed:
                    self._prefetch_batch(batch)
            except Exception as e:  # noqa: BLE001 - best-effort warming
                logger.debug("prefetch batch failed: %s", e)
            finally:
                with self._mu:
                    for h in batch:
                        self._inflight.discard(h)

    def _prefetch_batch(self, batch: List[int]) -> None:
        """Warm a whole submit's worth of blocks with batched fetches: one
        loopback round trip for the host-staged run, one multi-block DCN
        round trip per peer (instead of one connection + RTT per block)."""
        # A root trace of its own: this runs on the background prefetcher
        # thread, where no request trace is ever active.
        with obs.request("transfer.prefetch_batch", {"blocks": len(batch)}):
            self._prefetch_batch_impl(batch)

    def _prefetch_batch_impl(self, batch: List[int]) -> None:
        max_size = max(self.codec.page_nbytes, 1)
        with self._mu:
            todo = [h for h in batch if h not in self._ready]
            staged_set = {h for h in todo if h in self._staged}
        staged_run = [h for h in todo if h in staged_set]
        peer_runs: "OrderedDict[Tuple[str, int], List[int]]" = OrderedDict()
        if self.peer_resolver is not None:
            for h in todo:
                if h in staged_set:
                    continue
                addr = self.peer_resolver(h)
                if addr is not None:
                    peer_runs.setdefault(addr, []).append(h)
        fetched: List[tuple] = []  # (hash, payload, source) in chain order
        if staged_run:
            for h, payload in zip(
                staged_run, self._fetch_staged_many(staged_run, max_size)
            ):
                if payload is not None:
                    fetched.append((h, payload, STAGED))
        for addr, run in peer_runs.items():
            for h, payload in zip(run, self._fetch_peer_many(addr, run, max_size)):
                if payload is not None:
                    fetched.append((h, payload, PEER))
        if not fetched:
            return
        with self._mu:
            for h, payload, source in fetched:
                if h not in self._ready:
                    self._ready[h] = (payload, source)
            while len(self._ready) > self._ready_cap:
                self._ready.popitem(last=False)  # payload copies; no event
        self.stats["prefetched"] += len(fetched)

    def close(self) -> None:
        """Stop the prefetcher and stager (idempotent; safe when they never
        started). Pending batches drain unfetched/unresolved — see
        _prefetch_loop / _stager_loop."""
        with self._mu:
            # Under _mu: stage_async's closed-check is also under the lock,
            # so a racing free() can no longer register entries (and
            # _ensure_stager no longer spawns) after this point.
            self._closed = True
        if self._prefetch_thread is not None and self._prefetch_thread.is_alive():
            self._prefetch_q.put(None)
            self._prefetch_thread.join(timeout=5.0)
        self._prefetch_thread = None
        if self._stage_thread is not None and self._stage_thread.is_alive():
            self._stage_q.put(None)
            self._stage_thread.join(timeout=5.0)
        self._stage_thread = None

    # -- internals ---------------------------------------------------------

    def _stage_many(self, blocks: List[tuple]) -> int:
        """Stage blocks not already host-resident. `blocks`: (hash,
        token_ids, parent, page_id, lora_id). Returns how many of `blocks`
        are host-resident afterwards.

        Waves up to `stage_wave_pages` pay ONE extract dispatch. Bigger
        reclaim waves run double-buffered dispatch-then-drain: wave i+1's
        gather + D2H copy is dispatched BEFORE wave i's payloads are
        admitted, so the device→host DMA overlaps the admit's
        serialization + loopback TCP put + event emission instead of
        serializing behind it.

        Blocks with an in-flight eager snapshot (stage_async) are claimed
        and admitted inline — their content was captured at snapshot time
        and the host copy has been overlapping since, so this path pays
        only the residual sync instead of a fresh extract.

        A root trace in the flight recorder (`transfer.stage`), with
        extract dispatch/drain and host-store admits as child spans."""
        with obs.request("transfer.stage", {"blocks": len(blocks)}):
            return self._stage_many_impl(blocks)

    def _stage_many_impl(self, blocks: List[tuple]) -> int:
        fresh = []
        n_resident = 0
        pending_blocks = []
        pending_entries = []
        with self._mu:
            for block in blocks:
                if block[0] in self._staged:
                    self._staged.move_to_end(block[0])
                    n_resident += 1
                elif block[0] in self._pending_stage:
                    entry = self._pending_stage[block[0]]
                    pending_blocks.append(block)
                    if entry not in pending_entries:
                        pending_entries.append(entry)
                else:
                    fresh.append(block)
        for entry in pending_entries:
            # An entry may cover more blocks than requested; admitting the
            # superset is harmless (they were all freed together).
            self._resolve_entry(entry)
        # Count only the REQUESTED blocks that actually landed (the
        # superset's extras get counted by their own reclaim wave, if any)
        # and fall back to a synchronous extract for requested blocks whose
        # snapshot failed to admit — the page content is still valid here,
        # so losing the snapshot must not lose the block.
        with self._mu:
            for block in pending_blocks:
                if block[0] in self._staged:
                    n_resident += 1
                else:
                    fresh.append(block)
        if not fresh:
            return n_resident
        wave = self.stage_wave_pages
        if len(fresh) <= wave:
            with obs.stage("transfer.stage_extract"):
                payloads = self.codec.extract_many([b[3] for b in fresh])
            return n_resident + self._admit_payloads(fresh, payloads)
        # Dispatch-then-drain double buffering: at most one un-drained wave
        # in flight beyond the one being dispatched, so pending gather
        # outputs stay bounded at 2 waves of pages.
        pending: List[tuple] = []
        for start in range(0, len(fresh), wave):
            w = fresh[start:start + wave]
            try:
                with obs.stage("transfer.stage_extract"):
                    resolve = self.codec.extract_many_async([b[3] for b in w])
            except Exception as e:  # noqa: BLE001 - wave is best-effort
                logger.debug("stage wave dispatch failed: %s", e)
                continue
            pending.append((w, resolve))
            self.stats["stage_waves"] += 1
            if len(pending) >= 2:
                n_resident += self._drain_stage_wave(*pending.pop(0))
        for w, resolve in pending:
            n_resident += self._drain_stage_wave(w, resolve)
        return n_resident

    def _drain_stage_wave(self, blocks: List[tuple], resolve) -> int:
        try:
            with obs.stage("transfer.stage_drain"):
                payloads = resolve()
        except Exception as e:  # noqa: BLE001 - wave is best-effort
            logger.debug("stage wave resolve failed: %s", e)
            return 0
        return self._admit_payloads(blocks, payloads)

    def _admit_payloads(self, blocks: List[tuple], payloads: List[bytes]) -> int:
        """Admit extracted payloads to the host store (capacity-evicting).
        Returns how many landed."""
        with obs.stage("transfer.stage_admit"):
            return self._admit_payloads_impl(blocks, payloads)

    def _admit_payloads_impl(
        self, blocks: List[tuple], payloads: List[bytes]
    ) -> int:
        n_resident = 0
        for (chunk_hash, token_ids, parent_hash, _pid, lora_id), payload in zip(
            blocks, payloads
        ):
            victims: List[int] = []
            with self._mu:
                while len(self._staged) >= self.capacity_blocks:
                    victim, _ = self._staged.popitem(last=False)
                    victims.append(victim)
                    self.stats["host_evictions"] += 1
            # drop() is a server round-trip + event emission — keep it
            # outside the lock so membership checks never stall on I/O.
            for victim in victims:
                self.connector.drop(victim)
            # Per-block isolation: one failed stage must not drop the rest
            # of the wave from the host tier.
            try:
                self.connector.stage(
                    chunk_hash, payload, token_ids,
                    len(token_ids), parent_hash, lora_id,
                )
            except Exception as e:  # noqa: BLE001 - staging is best-effort
                logger.debug("stage failed for %x: %s", chunk_hash, e)
                continue
            with self._mu:
                self._staged[chunk_hash] = None
            n_resident += 1
        return n_resident

    # -- eager (overlapped) staging ----------------------------------------

    def stage_async(self, blocks: List[tuple]) -> int:
        """Begin staging off the critical path (VERDICT r4 #7 'overlap
        extract with compute'): snapshot the pages NOW — one enqueued
        gather whose device→host copy overlaps whatever compute is queued
        behind it — and admit the payloads from the background stager
        thread. A later reclaim finds the blocks either already staged or
        claimable in-flight, instead of paying a synchronous extract on
        the allocation path. Returns the number of snapshots initiated;
        blocks beyond the in-flight budget fall back to the synchronous
        reclaim-time stage."""
        if self._async_stage_cap <= 0 or not blocks:
            return 0
        with self._mu:
            if self._closed:
                return 0
            budget = self._async_stage_cap - self._pending_pages
            fresh = []
            for b in blocks:
                if budget <= 0:
                    break
                if b[0] in self._staged or b[0] in self._pending_stage:
                    continue
                fresh.append(b)
                budget -= 1
            if not fresh:
                return 0
            # Register under the lock (atomic with the membership check so
            # a concurrent stage_async can't double-snapshot), but keep the
            # codec call OUTSIDE it — device I/O under _mu would stall
            # every membership check. Claimants arriving before the
            # snapshot is enqueued wait on `ready`.
            entry = {
                "blocks": fresh, "resolve": None, "claimed": False,
                "ready": threading.Event(), "done": threading.Event(),
            }
            for b in fresh:
                self._pending_stage[b[0]] = entry
            self._pending_pages += len(fresh)
        try:
            entry["resolve"] = self.codec.extract_many_async(
                [b[3] for b in fresh]
            )
        except Exception as e:  # noqa: BLE001 - snapshot is best-effort
            # Unregister so the budget isn't leaked and the blocks fall
            # back to the synchronous reclaim-time stage.
            entry["ready"].set()
            self._claim_entry(entry)
            entry["done"].set()
            logger.debug("eager stage snapshot failed: %s", e)
            return 0
        entry["ready"].set()
        self._ensure_stager()
        self._stage_q.put(entry)
        return len(fresh)

    def _claim_entry(self, entry: dict) -> bool:
        """Exactly-once claim of an in-flight snapshot (the stager thread
        and an inline reclaim may race for it)."""
        with self._mu:
            if entry["claimed"]:
                return False
            entry["claimed"] = True
            for b in entry["blocks"]:
                self._pending_stage.pop(b[0], None)
            self._pending_pages -= len(entry["blocks"])
            return True

    def _resolve_entry(self, entry: dict) -> int:
        if not self._claim_entry(entry):
            # Another thread (stager vs inline reclaim) owns this entry:
            # wait for its admit so the caller's membership re-check sees
            # the landed blocks instead of paying a duplicate synchronous
            # extract for work already in flight.
            entry["done"].wait(timeout=30.0)
            return 0
        try:
            entry["ready"].wait(timeout=30.0)
            resolve = entry["resolve"]
            if resolve is None:  # snapshot enqueue itself failed
                return 0
            try:
                payloads = resolve()
            except Exception as e:  # noqa: BLE001 - best-effort snapshot
                logger.debug("eager stage resolve failed: %s", e)
                return 0
            return self._admit_payloads(entry["blocks"], payloads)
        finally:
            entry["done"].set()

    def _ensure_stager(self) -> None:
        if self._closed:
            return
        if self._stage_thread is None or not self._stage_thread.is_alive():
            self._stage_thread = threading.Thread(
                target=self._stager_loop, name="kv-tier-stager", daemon=True
            )
            self._stage_thread.start()

    def _stager_loop(self) -> None:
        while True:
            entry = self._stage_q.get()
            try:
                if entry is None:
                    return
                if not self._closed:
                    self._resolve_entry(entry)
                else:
                    self._claim_entry(entry)  # drop without resolving
                    entry["done"].set()
            except Exception as e:  # noqa: BLE001 - stager must not die
                logger.debug("eager stage failed: %s", e)
            finally:
                self._stage_q.task_done()

    def drain_async_stages(self) -> None:
        """Resolve every in-flight snapshot (test/shutdown helper): claims
        whatever is still pending inline, then waits for the stager thread
        to finish any entry it already claimed but has not admitted."""
        while True:
            with self._mu:
                entries = {
                    id(e): e for e in self._pending_stage.values()
                }
            if not entries:
                break
            for entry in entries.values():
                self._resolve_entry(entry)
        if self._stage_thread is not None and self._stage_thread.is_alive():
            self._stage_q.join()

    @property
    def staged_count(self) -> int:
        with self._mu:
            return len(self._staged)

    # -- residency-digest surface (antientropy/auditor.py) -----------------

    def staged_subset(self, chunk_hashes) -> set:
        """Membership answer over the challenged hashes: which of them are
        host-resident (staged, hence fetchable) RIGHT NOW. One lock
        crossing, no bytes moved — the cheap audit-challenge primitive."""
        with self._mu:
            return {h for h in chunk_hashes if h in self._staged}

    def staged_sample(self, limit: int) -> List[int]:
        """Bounded sample of host-resident hashes, oldest-staged first
        (the re-admit direction of a residency audit: blocks this pod
        holds that the index may have lost)."""
        if limit <= 0:
            return []
        import itertools

        with self._mu:
            return list(itertools.islice(self._staged, limit))


class IndexBackedPeerResolver:
    """Resolve a block hash to a peer pod's transfer address through the
    control-plane index — the routing loop closed over the data plane: the
    indexer knows which pod holds a block and at which tier; pods whose
    entry is host-tier have the bytes staged and fetchable."""

    def __init__(
        self,
        index,
        model_name: str,
        pod_addrs: Mapping[str, Tuple[str, int]],
        self_pod_id: str,
        host_tier: str = "host",
        rendezvous_primary: bool = False,
        negative_ttl_s: float = 3.0,
        clock: Callable[[], float] = None,
    ):
        self.index = index
        self.model_name = model_name
        self.pod_addrs = pod_addrs
        self.self_pod_id = self_pod_id
        self.host_tier = host_tier
        # False (default): the primary holder is the index's first
        # matching entry — the historical behavior, byte-compatible with
        # every committed bench. True: the primary is the per-(chunk,
        # pod) rendezvous winner, which is ORDER-INDEPENDENT — per-key
        # entry order races with the event pool's concurrent workers, so
        # replayable scenarios (the chaos bench) need a peer choice that
        # does not depend on worker interleaving.
        self.rendezvous_primary = rendezvous_primary
        # Negative-result cache: a peer that just answered "missing" for
        # a block (note_miss — wired off the TransferClient's
        # on_fetch_misses seam) is demoted from primary for THAT block
        # until the TTL lapses, instead of being re-picked on the very
        # next request while its phantom index entry awaits repair. Other
        # holders move ahead; a peer that is the ONLY holder is still
        # tried (a stale negative must not turn a fetchable block into a
        # permanent miss). With nothing calling note_miss the cache stays
        # empty and candidate order is byte-identical to the historical
        # behavior. <=0 disables.
        self.negative_ttl_s = negative_ttl_s
        import time as _time

        self.clock = clock or _time.monotonic
        self._negative: Dict[Tuple[Tuple[str, int], int], float] = {}
        self.negative_skips = 0

    def note_miss(
        self,
        addr: Tuple[str, int],
        chunk_hashes,
        now: Optional[float] = None,
    ) -> None:
        """Record per-(peer, block) explicit-miss answers for the TTL."""
        if self.negative_ttl_s <= 0:
            return
        if now is None:
            now = self.clock()
        for h in chunk_hashes:
            self._negative[(addr, h)] = now + self.negative_ttl_s
        if len(self._negative) > 4096:
            self._negative = {
                k: t for k, t in self._negative.items() if t > now
            }

    def forget_pod(self, pod_identifier: str) -> int:
        """Departure reap hook: drop every negative-cache entry addressed
        to the departed pod (resolved through `pod_addrs` by bare
        identity). Its phantom-miss memory protects nothing once the pod
        is gone, and a replacement pod reusing the address must not
        inherit its predecessor's disclaimers. Returns rows removed."""
        bare = base_pod_identifier(pod_identifier)
        addr = self.pod_addrs.get(pod_identifier) or self.pod_addrs.get(bare)
        if addr is None or not self._negative:
            return 0
        victims = [k for k in self._negative if k[0] == addr]
        for k in victims:
            self._negative.pop(k, None)
        return len(victims)

    def negative_entries(self) -> int:
        """Current negative-cache cardinality (the resourcegov meter)."""
        return len(self._negative)

    def _negatively_cached(
        self, addr: Tuple[str, int], chunk_hash: int, now: float
    ) -> bool:
        expiry = self._negative.get((addr, chunk_hash))
        if expiry is None:
            return False
        if expiry <= now:
            self._negative.pop((addr, chunk_hash), None)
            return False
        return True

    def __call__(self, chunk_hash: int) -> Optional[Tuple[str, int]]:
        ranked = self.candidates(chunk_hash)
        return ranked[0] if ranked else None

    def candidates(self, chunk_hash: int) -> List[Tuple[str, int]]:
        """Every fetchable holder of a block, primary first. By default
        the primary is the index's first matching entry (the historical
        `__call__` pick — the healthy path stays bit-identical) and the
        remaining holders follow in per-(chunk, pod) rendezvous order, so
        hedge traffic for a hot block spreads across its replicas instead
        of piling onto one alternate. With `rendezvous_primary` the WHOLE
        list is rendezvous-ordered (order-independent peer choice)."""
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.hashing import (
            fnv64a,
            fold64,
        )

        key = Key(self.model_name, chunk_hash)
        hits = self.index.lookup([key], set())
        holders = []  # (rendezvous weight, index order, addr)
        seen = set()
        for order, entry in enumerate(hits.get(key, [])):
            # Compare/resolve by bare pod identity: DP-ranked engines index
            # as "pod@dpR" but the address map (and we) know bare pod ids.
            bare = base_pod_identifier(entry.pod_identifier)
            if bare == base_pod_identifier(self.self_pod_id):
                continue
            if entry.device_tier != self.host_tier:
                continue  # only staged blocks are fetchable
            addr = (
                self.pod_addrs.get(entry.pod_identifier)
                or self.pod_addrs.get(bare)
            )
            if addr is None or addr in seen:
                continue
            seen.add(addr)
            holders.append((fold64(fnv64a(bare.encode()), chunk_hash), order, addr))
        if not holders:
            return []
        if self.rendezvous_primary:
            holders.sort()
            ranked = [addr for _w, _o, addr in holders]
        else:
            first = holders[0]
            rest = sorted(holders[1:])
            ranked = [first[2]] + [addr for _w, _o, addr in rest]
        if not self._negative:
            return ranked
        # Negative-result demotion: holders that just disclaimed this
        # block drop behind the fresh ones (kept — they may be the only
        # holder, and the TTL bounds how long a stale negative can lie).
        now = self.clock()
        fresh = [
            a for a in ranked if not self._negatively_cached(a, chunk_hash, now)
        ]
        if not fresh or fresh[0] == ranked[0]:
            return ranked
        self.negative_skips += 1
        metrics.count_negative_cache_skip()
        return fresh + [a for a in ranked if a not in fresh]

"""Two-tier KV policy: wires kv_connectors into the serving loop.

The reference plans this behavior but never implements it (its
kv_connectors/ directory is empty; its device tiers "gpu"/"cpu" exist only
as scoring weights). Here the tiers are real:

- **reclaim → offload**: when the block manager reclaims a committed HBM
  page under allocation pressure, the page's bytes are staged in the host
  store (C++ transfer server) instead of vanishing — BlockRemoved(hbm) +
  BlockStored(host) flow to the control plane, so the scorer keeps ranking
  this pod for the block at the host-tier weight.
- **miss → restore/onboard**: when an allocation's hash chain misses in
  HBM, the block is materialized from the host store, or — if a peer
  resolver is configured — fetched from another pod's transfer server over
  DCN, landing as a normal committed page (device-tier BlockStored). Pod B
  can thereby serve a prefix it never computed.
- **export**: explicit staging of a live sequence's committed pages
  (prefill/decode disaggregation push): the pages stay in HBM, a copy
  becomes fetchable by peers.

The page payload is opaque bytes; `PageCodec` implementations serialize one
logical page across all layers (bf16 pair or int8 quantized 4-tuple).
Accounting-only pods use `NullPageCodec` — the full event/scoring behavior
without device bytes, which is what the fleet bench simulates.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import TYPE_CHECKING, Callable, Dict, List, Mapping, Optional, Tuple

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import (
    Key,
    base_pod_identifier,
)
from llm_d_kv_cache_manager_tpu.utils import logging as kvlog

if TYPE_CHECKING:  # kv_connectors loads the ctypes lib; keep it optional at
    from llm_d_kv_cache_manager_tpu.kv_connectors.connector import (  # runtime
        KVConnector,
    )

logger = kvlog.get_logger("engine.tiering")

# (host, port) of a peer pod's transfer server, or None.
PeerResolver = Callable[[int], Optional[Tuple[str, int]]]


class PageCodec:
    """Serializes one logical KV page (all layers) to/from opaque bytes."""

    page_nbytes: int = 0

    def extract(self, page_id: int) -> bytes:
        raise NotImplementedError

    def insert(self, page_id: int, payload: bytes) -> None:
        raise NotImplementedError


class NullPageCodec(PageCodec):
    """Accounting-only pods: zero-byte payloads, full event behavior."""

    def extract(self, page_id: int) -> bytes:
        return b""

    def insert(self, page_id: int, payload: bytes) -> None:
        if payload:
            raise ValueError("accounting-only pod received a non-empty block")


class TieredKVStore:
    """Per-pod two-tier policy over a KVConnector.

    Bounded host store: staging beyond `capacity_blocks` drops the
    least-recently-staged block first (BlockRemoved(host) via the
    connector), so host RAM use is capped like any cache tier.
    """

    def __init__(
        self,
        connector: "KVConnector",
        codec: PageCodec,
        capacity_blocks: int = 1024,
        peer_resolver: Optional[PeerResolver] = None,
    ):
        self.connector = connector
        self.codec = codec
        self.capacity_blocks = capacity_blocks
        self.peer_resolver = peer_resolver
        # hash -> None, insertion-ordered: the host store's eviction queue.
        self._staged: "OrderedDict[int, None]" = OrderedDict()
        self.stats: Dict[str, int] = {
            "offloads": 0, "restores": 0, "onboards": 0, "host_evictions": 0,
        }

    # -- BlockManager hook: reclaim → offload ------------------------------

    def reclaim_hook(
        self, chunk_hash: int, token_ids: List[int],
        parent_hash: Optional[int], page_id: int,
        lora_id: Optional[int] = None,
    ) -> None:
        self._stage(chunk_hash, token_ids, parent_hash, page_id, lora_id)
        self.stats["offloads"] += 1

    # -- P/D disaggregation: stage without reclaiming ----------------------

    def export_block(
        self, chunk_hash: int, token_ids: List[int],
        parent_hash: Optional[int], page_id: int,
        lora_id: Optional[int] = None,
    ) -> None:
        self._stage(chunk_hash, token_ids, parent_hash, page_id, lora_id)

    # -- BlockManager hook: miss → restore/onboard -------------------------

    def page_loader(
        self, chunk_hash: int, token_ids: List[int],
        parent_hash: Optional[int], page_id: int,
    ) -> bool:
        # _staged exactly mirrors the local server's contents, so a miss
        # there skips the loopback round trip on the allocation hot path.
        if chunk_hash in self._staged:
            payload = self.connector.fetch_staged(
                chunk_hash, max(self.codec.page_nbytes, 1)
            )
            if payload is not None:
                self.codec.insert(page_id, payload)
                self.stats["restores"] += 1
                return True
        if self.peer_resolver is not None:
            addr = self.peer_resolver(chunk_hash)
            if addr is not None:
                payload = self.connector.onboard_payload(
                    addr[0], addr[1], chunk_hash, max(self.codec.page_nbytes, 1)
                )
                if payload is not None:
                    self.codec.insert(page_id, payload)
                    self.stats["onboards"] += 1
                    return True
        return False

    # -- internals ---------------------------------------------------------

    def _stage(
        self, chunk_hash: int, token_ids: List[int],
        parent_hash: Optional[int], page_id: int,
        lora_id: Optional[int] = None,
    ) -> None:
        if chunk_hash in self._staged:
            self._staged.move_to_end(chunk_hash)
            return
        while len(self._staged) >= self.capacity_blocks:
            victim, _ = self._staged.popitem(last=False)
            self.connector.drop(victim)
            self.stats["host_evictions"] += 1
        self.connector.stage(
            chunk_hash, self.codec.extract(page_id), token_ids,
            len(token_ids), parent_hash, lora_id,
        )
        self._staged[chunk_hash] = None

    @property
    def staged_count(self) -> int:
        return len(self._staged)


class IndexBackedPeerResolver:
    """Resolve a block hash to a peer pod's transfer address through the
    control-plane index — the routing loop closed over the data plane: the
    indexer knows which pod holds a block and at which tier; pods whose
    entry is host-tier have the bytes staged and fetchable."""

    def __init__(
        self,
        index,
        model_name: str,
        pod_addrs: Mapping[str, Tuple[str, int]],
        self_pod_id: str,
        host_tier: str = "host",
    ):
        self.index = index
        self.model_name = model_name
        self.pod_addrs = pod_addrs
        self.self_pod_id = self_pod_id
        self.host_tier = host_tier

    def __call__(self, chunk_hash: int) -> Optional[Tuple[str, int]]:
        key = Key(self.model_name, chunk_hash)
        hits = self.index.lookup([key], set())
        for entry in hits.get(key, []):
            # Compare/resolve by bare pod identity: DP-ranked engines index
            # as "pod@dpR" but the address map (and we) know bare pod ids.
            bare = base_pod_identifier(entry.pod_identifier)
            if bare == base_pod_identifier(self.self_pod_id):
                continue
            if entry.device_tier != self.host_tier:
                continue  # only staged blocks are fetchable
            addr = self.pod_addrs.get(entry.pod_identifier) or self.pod_addrs.get(bare)
            if addr is not None:
                return addr
        return None

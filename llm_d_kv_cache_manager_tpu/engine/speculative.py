"""Speculative decoding: draft proposes, target verifies in one pass.
Greedy by default; SpeculativeDecoder also implements SPECULATIVE SAMPLING
(accept with min(1, q/p), resample rejections from the residual), whose
emitted-token law is exactly the target's filtered sampling distribution.

TPU-first rationale: decode is bandwidth-bound (one token streams the whole
weight stack), but the MXU can score k+1 positions for nearly the price of
one. A small draft model proposes k tokens autoregressively; the target
then runs a single `prefill_cache(all_logits=True)` over the proposals —
one weight stream amortized over k positions — and accepts the longest
prefix whose greedy argmax chain matches. Every emitted token is the
argmax of TARGET logits, so the sampling rule is exactly target-only
greedy; the tests pin bit-identical output on f32 models. (In bf16 the
dense verify path and the paged decode path can round differently, so a
near-exact logit tie may resolve differently than plain decode would —
the same numerics caveat batched-vs-isolated decode already carries.)

Integration with the serving stack:
- the target sequence lives in the pod's real BlockManager: proposals'
  KV lands in pages reserved ahead (`reserve_pages`), and only ACCEPTED
  tokens are appended (so BlockStored events / prefix-cache commits never
  advertise unverified content). Rejected positions leave stale device
  rows beyond seq_len — masked by attention and overwritten by the next
  round, exactly like vLLM's rejected draft slots.
- the draft keeps a private paged cache (its own page pool, identity block
  table); after each round it catches up on the accepted tokens it did
  not itself propose.

Reference anchor: none (the reference executes no model math); vLLM's
speculative decoding is the behavioral anchor.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from llm_d_kv_cache_manager_tpu.engine.block_manager import OutOfPagesError
from llm_d_kv_cache_manager_tpu.engine.engine import EnginePod
from llm_d_kv_cache_manager_tpu.models import llama


@dataclass
class SpeculativeStats:
    proposed: int = 0
    accepted: int = 0
    rounds: int = 0

    @property
    def acceptance_rate(self) -> float:
        return self.accepted / self.proposed if self.proposed else 0.0


class _DraftState:
    """The draft model's private paged cache for one sequence."""

    def __init__(self, config, params, max_tokens: int, page_size: int):
        self.config = config
        self.params = params
        self.page_size = page_size
        n_pages = (max_tokens + page_size - 1) // page_size + 1
        self.cache = llama.make_kv_pages(config, n_pages, page_size)
        self.table = jnp.arange(n_pages, dtype=jnp.int32)
        self.n_tokens = 0  # positions with valid KV

    def ingest(self, tokens: List[int]) -> jax.Array:
        """Write KV for `tokens` at the current position; returns the
        last-position logits (the draft's next proposal seed). Single
        tokens ride the O(seq_len) paged decode path; multi-token catch-up
        chunks use prefill."""
        if len(tokens) == 1:
            self.cache, logits = llama.decode_step_cache(
                self.config, self.params, self.cache,
                jnp.asarray(tokens, jnp.int32),
                self.table[None],
                jnp.asarray([self.n_tokens], jnp.int32),
            )
            self.n_tokens += 1
            return logits[0]
        chunk = jnp.asarray(tokens, dtype=jnp.int32)
        self.cache, logits = llama.prefill_cache(
            self.config, self.params, self.cache, chunk, self.table,
            self.n_tokens,
        )
        self.n_tokens += len(tokens)
        return logits


class SpeculativeDecoder:
    """Single-sequence greedy generation with draft-model speculation."""

    def __init__(
        self,
        pod: EnginePod,
        draft_config,
        draft_params,
        k: int = 4,
    ):
        if pod._model is None:
            raise ValueError("SpeculativeDecoder requires with_model=True")
        if pod.lora_stack is not None:
            raise NotImplementedError("speculative decoding with LoRA adapters")
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        self.pod = pod
        self.draft_config = draft_config
        self.draft_params = draft_params
        self.k = k
        self.stats = SpeculativeStats()
        self._gen_counter = 0  # unseeded sampled calls get distinct streams

    def generate(
        self,
        prompt_tokens: List[int],
        max_new_tokens: int,
        eos_token: Optional[int] = None,
        sampling=None,  # ops.sampling.SamplingParams; None/greedy => argmax
    ) -> List[int]:
        """Greedy by default. With non-greedy `sampling`, runs SPECULATIVE
        SAMPLING (Leviathan et al.): the draft SAMPLES proposals from its
        own filtered distribution, the target accepts each with
        min(1, q/p) and resamples the first rejection from the residual
        max(0, q-p) — the emitted-token law is exactly the target's
        filtered distribution q (pinned statistically in tests). Both
        distributions pass through the SAME filter_logits the plain
        scheduler samples with. Draft/accept randomness rides independent
        per-position key streams split from PRNGKey(seed), so a given seed
        reproduces its output."""
        pod = self.pod
        page_size = pod.config.page_size
        max_total = len(prompt_tokens) + max_new_tokens + self.k + 1

        sampled_mode = sampling is not None and not sampling.is_greedy
        if sampled_mode:
            from llm_d_kv_cache_manager_tpu.ops.sampling import (
                accept_or_resample,
                filter_logits,
                sample_tokens,
            )

            # Unseeded calls draw a fresh per-call stream (else best-of-n
            # sampling would collapse to n identical sequences); seeded
            # calls reproduce exactly.
            self._gen_counter += 1
            base = jax.random.PRNGKey(
                sampling.seed if sampling.seed is not None
                else self._gen_counter
            )
            # Independent streams: target emissions, draft proposals,
            # accept/resample draws — each folded per absolute position.
            k_target, k_draft, k_accept = jax.random.split(base, 3)
            sp_arrays = (
                jnp.asarray([sampling.temperature], jnp.float32),
                jnp.asarray([sampling.top_k], jnp.int32),
                jnp.asarray([sampling.top_p], jnp.float32),
            )

            def q_of(logits_row):  # filtered target distribution
                return jax.nn.softmax(
                    filter_logits(logits_row[None], *sp_arrays)[0]
                )

            def draw(logits_row, stream, position):
                # The jitted batched sampler at B=1: one dispatch per draw.
                key = jax.random.fold_in(stream, position)
                return int(sample_tokens(
                    logits_row[None], *sp_arrays, key[None]
                )[0])

        state, _ = pod.prefill(list(prompt_tokens))
        draft = _DraftState(
            self.draft_config, self.draft_params, max_total, page_size
        )
        draft_logits = draft.ingest(list(prompt_tokens))

        generated: List[int] = []
        target_logits = pod.last_logits  # target's opinion at the frontier
        # A residual resample whose KV is not yet resident; consumed as the
        # next round's t0 (sampled mode only).
        pending: Optional[int] = None

        try:
            while len(generated) < max_new_tokens:
                # The frontier token: a carried residual resample, else the
                # target's own choice (greedy argmax, or a draw from its
                # filtered distribution).
                pos_t0 = len(state.tokens)  # device position t0 will occupy
                if pending is not None:
                    t0, pending = pending, None
                elif sampled_mode:
                    t0 = draw(target_logits, k_target, pos_t0)
                else:
                    t0 = int(jnp.argmax(target_logits))

                # Cap proposals at what could possibly be accepted: the
                # remaining token budget after t0, and the sequence's page
                # capacity (reserving past max_pages_per_seq would crash a
                # generation that plain decode finishes fine).
                capacity_tokens = (
                    pod.config.max_pages_per_seq * page_size - pos_t0 - 1
                )
                k_eff = max(
                    0,
                    min(self.k, max_new_tokens - len(generated) - 1,
                        capacity_tokens),
                )

                # Draft proposes k_eff tokens after t0 (autoregressive;
                # greedy argmax, or sampled from ITS filtered distribution
                # — recorded so acceptance can form q/p). In the final
                # stretch (k_eff == 0) the draft is skipped entirely.
                proposals: List[int] = []
                draft_dists = []  # sampled mode: p_i(·) per proposal
                if k_eff > 0:
                    seed_logits = draft.ingest([t0])
                    for j in range(k_eff):
                        if sampled_mode:
                            f = filter_logits(seed_logits[None], *sp_arrays)[0]
                            draft_dists.append(jax.nn.softmax(f))
                            g = jax.random.gumbel(
                                jax.random.fold_in(
                                    k_draft, pos_t0 + 1 + j
                                ),
                                f.shape,
                            )
                            p = int(jnp.argmax(f + g))
                        else:
                            p = int(jnp.argmax(seed_logits))
                        proposals.append(p)
                        seed_logits = draft.ingest([p])
                self.stats.proposed += len(proposals)
                self.stats.rounds += 1

                # Target verifies all proposals in ONE pass. The chunk
                # starts with t0 (whose KV is not yet in the cache);
                # logits[i] is the target's next-token opinion after
                # chunk[i], so logits[i] vs proposals[i] is the acceptance
                # test and logits[n_accept] seeds the next round.
                chunk = [t0] + proposals
                pod.block_manager.reserve_pages(
                    state,
                    (pos_t0 + len(chunk) + page_size - 1) // page_size,
                )
                pod.kv_cache, verify_logits = llama.prefill_cache(
                    pod._model_config, pod.params, pod.kv_cache,
                    jnp.asarray(chunk, jnp.int32),
                    pod._padded_table(state), pos_t0,
                    all_logits=True,
                )

                resampled: Optional[int] = None
                if sampled_mode and proposals:
                    # Accept proposal i with prob min(1, q_i(x)/p_i(x));
                    # the first rejection resamples from the residual. All
                    # k (token, accepted) pairs are independent given the
                    # two distribution stacks, so ONE vmapped dispatch
                    # computes them and a host scan finds the first
                    # rejection (vs k sequential dispatch+sync pairs).
                    qs = jax.vmap(q_of)(verify_logits[: len(proposals)])
                    toks_a, oks = jax.vmap(accept_or_resample)(
                        qs, jnp.stack(draft_dists),
                        jnp.asarray(proposals, jnp.int32),
                        jax.vmap(jax.random.fold_in, (None, 0))(
                            k_accept,
                            pos_t0 + 1 + jnp.arange(len(proposals)),
                        ),
                    )
                    oks = np.asarray(oks)
                    toks_a = np.asarray(toks_a)
                    n_accept = 0
                    for i in range(len(proposals)):
                        if oks[i]:
                            n_accept += 1
                        else:
                            resampled = int(toks_a[i])
                            break
                elif sampled_mode:
                    n_accept = 0
                else:
                    argmaxes = np.asarray(jnp.argmax(verify_logits, axis=-1))
                    n_accept = 0
                    for i, p in enumerate(proposals):
                        if int(argmaxes[i]) != p:
                            break
                        n_accept += 1
                self.stats.accepted += n_accept

                done = False
                for tok in [t0] + proposals[:n_accept]:
                    if self._push(state, generated, tok, eos_token,
                                  max_new_tokens):
                        done = True
                        break
                if done:
                    break

                # Draft already holds KV for t0 + all proposals; on partial
                # acceptance its tail (like the target's) is stale-but-
                # masked. Rewind its valid-token count to the accepted
                # frontier so the next ingest overwrites the stale rows.
                # (k_eff == 0 rounds never touched the draft — and k_eff is
                # monotonic, so it stays untouched.)
                if k_eff > 0:
                    draft.n_tokens = len(state.tokens)
                if resampled is not None:
                    # The residual draw replaces the rejected proposal, but
                    # its KV is NOT resident (the verify pass wrote the
                    # proposal's row). Carry it as the NEXT round's t0: that
                    # round's verify chunk recomputes the position with the
                    # right token — the same pending-token convention plain
                    # decode uses.
                    pending = resampled
                    target_logits = None  # unused until a non-pending round
                else:
                    target_logits = verify_logits[n_accept]
        finally:
            pod.free(state)
        return generated

    def _push(
        self, state, generated: List[int], token: int,
        eos_token: Optional[int], max_new_tokens: int,
    ) -> bool:
        """Append one ACCEPTED token to the real sequence (block-manager
        accounting + events). Returns True when generation is finished."""
        generated.append(token)
        if eos_token is not None and token == eos_token:
            return True
        if len(generated) >= max_new_tokens:
            return True
        self.pod.block_manager.append_token(state, token)
        # Unlike plain decode, the pushed token's KV is ALREADY resident
        # (the verify pass wrote the whole chunk), so it is not pending:
        # commit any page it completed.
        self.pod.block_manager.mark_decode_computed(state)
        return False


class SpeculativeScheduler:
    """Continuous batching WITH speculation: the whole running batch drafts
    and verifies together.

    Per tick: k batched draft decode steps propose k tokens per running
    sequence, then ONE `verify_step_cache` pass scores every (sequence,
    position) — the target's weight stream is amortized over B·(k+1)
    positions, where per-sequence speculation would stream it B times.
    Admission (chunked prefill), preemption, paging, and events all ride
    the inner Scheduler unchanged, and the tick preserves the plain
    scheduler's invariant — each running sequence always carries exactly
    one appended-but-not-yet-KV-computed "pending" token — so greedy
    output is identical to the non-speculative scheduler (pinned by tests
    on f32): the verify chunk is [pending] + proposals, acceptance emits
    matching proposals, and the correction token becomes the next pending.

    The draft keeps one private page-pool stripe per batch slot; slots are
    assigned at admission and recycled on finish/preemption (a preempted
    request's draft state is discarded and rebuilt on re-admission).
    """

    def __init__(
        self,
        pod: EnginePod,
        draft_config,
        draft_params,
        k: int = 4,
        max_batch: int = 8,
        prefill_token_budget: int = 512,
    ):
        from llm_d_kv_cache_manager_tpu.engine.scheduler import Scheduler

        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        self.inner = Scheduler(pod, max_batch=max_batch,
                               prefill_token_budget=prefill_token_budget)
        self.pod = pod
        self.k = k
        self.draft_config = draft_config
        self.draft_params = draft_params
        self.stats = SpeculativeStats()

        page_size = pod.config.page_size
        self._stripe_pages = pod.config.max_pages_per_seq
        # +1: a shared draft trash page. Each slot's table carries it as a
        # final extra column, so a draft write past the stripe's capacity
        # (a rectangular k-window overrunning one sequence's headroom)
        # clamps into the trash page instead of corrupting a real row.
        n_draft_pages = max_batch * self._stripe_pages + 1
        self._draft_trash = n_draft_pages - 1
        self._draft_cache = llama.make_kv_pages(
            draft_config, n_draft_pages, page_size
        )
        self._free_slots = list(range(max_batch))
        # Host-side per-slot stripe index rows (constant): avoids a
        # device round trip per running request per tick.
        self._slot_tables = np.stack([
            np.concatenate([
                np.arange(i * self._stripe_pages, (i + 1) * self._stripe_pages,
                          dtype=np.int32),
                np.asarray([self._draft_trash], dtype=np.int32),
            ])
            for i in range(max_batch)
        ])
        # req_id -> [slot, draft_pos]; draft_pos counts positions with
        # valid draft KV (always == len(state.tokens) - 1: everything but
        # the pending token).
        self._draft_state: dict = {}

    # -- public API mirroring Scheduler ------------------------------------

    def submit(self, prompt_tokens, max_new_tokens=16, eos_token=None,
               lora_id=None, sampling=None):
        """LoRA requests speculate too: the TARGET verifies with the
        sequence's adapter (verify_step_cache lora), so emitted tokens are
        exactly adapter-greedy; the draft proposes with its base weights —
        adapter drift only lowers acceptance, never correctness.

        Sampled requests run BATCHED speculative sampling: the draft
        samples proposals from its filtered distribution, acceptance is
        min(1, q/p) per position, the first rejection's residual draw (or
        the bonus draw on full acceptance) becomes the next pending token
        — the emitted law is the target's filtered distribution, same
        rule as SpeculativeDecoder. Greedy and sampled requests mix in
        one batch."""
        return self.inner.submit(prompt_tokens, max_new_tokens, eos_token,
                                 lora_id=lora_id, sampling=sampling)

    @property
    def has_work(self) -> bool:
        return self.inner.has_work

    def run(self):
        results = {}
        while self.has_work:
            for req in self.step():
                results[req.req_id] = req.generated
        return results

    # -- internals ----------------------------------------------------------

    def _draft_table(self, slot: int):
        start = slot * self._stripe_pages
        return jnp.arange(start, start + self._stripe_pages, dtype=jnp.int32)

    def _sync_new_runners(self) -> None:
        """Admissions since last tick: assign a draft slot and ingest the
        request's history up to (excluding) the pending token — the tick's
        seed ingest covers pending itself."""
        for req in self.inner._running:
            if req.req_id in self._draft_state:
                continue
            slot = self._free_slots.pop()
            history = list(req.state.tokens[:-1])
            if history:
                self._draft_cache, _ = llama.prefill_cache(
                    self.draft_config, self.draft_params, self._draft_cache,
                    jnp.asarray(history, jnp.int32), self._draft_table(slot), 0,
                )
            self._draft_state[req.req_id] = [slot, len(history)]
        # Reap state of requests that left the running set outside our
        # acceptance path (e.g. admission-time EOS or preemption).
        running_ids = {r.req_id for r in self.inner._running}
        for rid in list(self._draft_state):
            if rid not in running_ids:
                self._release(rid)

    def _release(self, req_id: int) -> None:
        slot_pos = self._draft_state.pop(req_id, None)
        if slot_pos is not None:
            self._free_slots.append(slot_pos[0])

    def step(self):
        finished = self.inner._rejected
        self.inner._rejected = []
        finished += self.inner._prefill_tick()
        self._sync_new_runners()
        finished += self._spec_decode()
        return finished

    def _spec_decode(self):
        running = self.inner._running
        if not running:
            return []
        pod = self.pod
        page_size = pod.config.page_size

        # Per-sequence acceptance budgets (ADVICE r2: mask per sequence,
        # don't clamp the window to the weakest sequence). accepts[i] =
        # how many PROPOSALS sequence i may keep this round, bounded by its
        # remaining token budget and page capacity; the rectangular chunk
        # width is sized to the strongest sequence, and weaker sequences'
        # overrun rows are steered to the pod's trash page.
        accepts = []
        for req in running:
            capacity = self._stripe_pages * page_size - len(req.state.tokens)
            budget = req.max_new_tokens - len(req.generated) - 1
            b_i = max(0, min(self.k, capacity, budget))
            # Reserve real pages for the rows this sequence may retain
            # (positions len-1 .. len+b_i-1). On pool exhaustion degrade
            # straight to b_i=0 — a pure decode step through the verify op
            # needs no new pages (the pending row's page is already held) —
            # rather than preempting the sequence.
            if b_i > 0:
                try:
                    pod.block_manager.reserve_pages(
                        req.state,
                        (len(req.state.tokens) + b_i + page_size - 1)
                        // page_size,
                    )
                except OutOfPagesError:
                    b_i = 0
            accepts.append(b_i)
        k_eff = max(accepts)  # chunk width: strongest sequence's budget

        b = len(running)
        # Batch axis padded to a power-of-2 bucket, like the plain
        # scheduler's decode: otherwise every distinct running count
        # compiles its own draft-step and verify program. Pad rows carry
        # all-trash tables (draft trash column / pod trash page) and
        # max_len 0, so their discarded steps never touch real pages.
        b_pad = pod.batch_bucket(b)
        pending = np.zeros((b_pad,), dtype=np.int32)
        pending[:b] = [req.state.tokens[-1] for req in running]
        starts = np.zeros((b_pad,), np.int32)
        starts[:b] = [len(r.state.tokens) - 1 for r in running]

        # Batched speculative SAMPLING state (rows with non-greedy
        # SamplingParams): per-row filter params and three independent
        # per-request key streams (draft proposals / accept draws /
        # emission draws), all folded per absolute position. Greedy rows
        # keep temperature 0 and ride the argmax paths untouched.
        sampled_rows = [
            r.sampling is not None and not r.sampling.is_greedy
            for r in running
        ]
        any_sampled = any(sampled_rows)
        if any_sampled:
            from llm_d_kv_cache_manager_tpu.ops.sampling import (
                accept_or_resample,
                filter_logits,
                position_keys,
                sample_tokens,
            )

            sp_temps = np.zeros((b_pad,), np.float32)
            sp_tks = np.zeros((b_pad,), np.int32)
            sp_tps = np.ones((b_pad,), np.float32)
            bases = [jax.random.PRNGKey(0)] * b_pad
            for i, r in enumerate(running):
                if sampled_rows[i]:
                    sp = r.sampling
                    sp_temps[i] = sp.temperature
                    sp_tks[i] = sp.top_k
                    sp_tps[i] = sp.top_p
                    bases[i] = jax.random.PRNGKey(
                        sp.seed if sp.seed is not None else r.req_id
                    )
            streams = jax.vmap(lambda k: jax.random.split(k, 3))(
                jnp.stack(bases)
            )  # [b_pad, 3, ...]
            emit_keys, draft_keys, accept_keys = (
                streams[:, 0], streams[:, 1], streams[:, 2]
            )
            sp_temps = jnp.asarray(sp_temps)
            sp_tks = jnp.asarray(sp_tks)
            sp_tps = jnp.asarray(sp_tps)

        # Batched draft proposals: ingest pending as the seed, then k_eff
        # autoregressive steps. Draft writes past a stripe's capacity clamp
        # into the shared draft trash column (see __init__) — garbage
        # proposals there are harmless, acceptance is target-based.
        proposals = np.zeros((b_pad, k_eff), dtype=np.int32)
        if k_eff > 0:
            draft_tables = np.full(
                (b_pad, self._slot_tables.shape[1]), self._draft_trash,
                dtype=np.int32,
            )
            draft_tables[:b] = self._slot_tables[
                [self._draft_state[r.req_id][0] for r in running]
            ]
            tables = jnp.asarray(draft_tables)
            draft_pos = np.zeros((b_pad,), dtype=np.int32)
            draft_pos[:b] = [self._draft_state[r.req_id][1] for r in running]
            cur = jnp.asarray(pending)
            draft_dists = []  # sampled mode: p_j(.) [b_pad, V] per column
            for j in range(k_eff):
                lens = jnp.asarray(draft_pos + j)
                self._draft_cache, logits = llama.decode_step_cache(
                    self.draft_config, self.draft_params, self._draft_cache,
                    cur, tables, lens,
                )
                if any_sampled:
                    # Proposal j occupies absolute position starts + 1 + j.
                    draft_dists.append(jax.nn.softmax(
                        filter_logits(logits, sp_temps, sp_tks, sp_tps),
                        axis=-1,
                    ))
                    cur = sample_tokens(
                        logits, sp_temps, sp_tks, sp_tps,
                        position_keys(
                            draft_keys, jnp.asarray(starts + 1 + j)
                        ),
                    )
                else:
                    cur = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                proposals[:, j] = np.asarray(cur)
            # Ingest the final proposal's KV too (its logits are unused):
            # without this, a fully accepted round leaves a permanent
            # zero-KV hole in the draft cache at that position.
            self._draft_cache, _ = llama.decode_step_cache(
                self.draft_config, self.draft_params, self._draft_cache,
                cur, tables, jnp.asarray(draft_pos + k_eff),
            )
            self.stats.proposed += b * k_eff
        self.stats.rounds += 1

        # One batched target verification over [pending, proposals...],
        # with per-sequence row allowances: sequence i's rows land in real
        # pages up to position len+accepts[i]-1 and in the trash page past
        # that.
        chunk = np.concatenate([pending[:, None], proposals], axis=1)
        max_lens = np.zeros((b_pad,), np.int32)  # pad rows: all writes → trash
        max_lens[:b] = [
            len(r.state.tokens) + a for r, a in zip(running, accepts)
        ]
        need = max(len(r.state.block_table) for r in running)
        bucket = pod.table_bucket(need)
        tables = np.full((b_pad, bucket), pod.trash_page, dtype=np.int32)
        for i, req in enumerate(running):
            tables[i, : len(req.state.block_table)] = req.state.block_table
        lora_ids = [r.lora_id for r in running] + [None] * (b_pad - b)
        pod.kv_cache, verify_logits = llama.verify_step_cache(
            pod._model_config, pod.params, pod.kv_cache,
            jnp.asarray(chunk), jnp.asarray(tables), jnp.asarray(starts),
            jnp.asarray(max_lens), pod.trash_page,
            lora=pod.lora_for_decode(lora_ids),
        )
        argmaxes = np.asarray(jnp.argmax(verify_logits, axis=-1))  # [B, k+1]

        if any_sampled:
            # Batched accept/resample draws for columns 0..k_eff-1 and
            # emission draws (bonus on full acceptance / plain draw at
            # accepts[i]==0) for every column — a few batched dispatches,
            # consumed per-row on host. Column j of a row sits at absolute
            # position starts + 1 + j.
            vocab = verify_logits.shape[-1]
            cols1 = k_eff + 1
            pos_mat = starts[:, None] + 1 + np.arange(cols1)[None, :]
            rep = lambda a, n: jnp.repeat(a, n, axis=0)
            emit_flat = sample_tokens(
                verify_logits.reshape(b_pad * cols1, vocab),
                rep(sp_temps, cols1), rep(sp_tks, cols1), rep(sp_tps, cols1),
                position_keys(
                    rep(emit_keys, cols1),
                    jnp.asarray(pos_mat.reshape(-1)),
                ),
            )
            emit_draws = np.asarray(emit_flat).reshape(b_pad, cols1)
            if k_eff > 0:
                q_all = jax.nn.softmax(filter_logits(
                    verify_logits.reshape(b_pad * cols1, vocab),
                    rep(sp_temps, cols1), rep(sp_tks, cols1),
                    rep(sp_tps, cols1),
                ), axis=-1).reshape(b_pad, cols1, vocab)
                toks_a, oks = jax.vmap(accept_or_resample)(
                    q_all[:, :k_eff].reshape(b_pad * k_eff, vocab),
                    jnp.stack(draft_dists, axis=1).reshape(
                        b_pad * k_eff, vocab
                    ),
                    jnp.asarray(proposals.reshape(-1), jnp.int32),
                    position_keys(
                        rep(accept_keys, k_eff),
                        jnp.asarray(pos_mat[:, :k_eff].reshape(-1)),
                    ),
                )
                accept_toks = np.asarray(toks_a).reshape(b_pad, k_eff)
                accept_oks = np.asarray(oks).reshape(b_pad, k_eff)

        # The verify pass wrote KV for every sequence's pending token (and
        # its proposals): the pending row is now resident, so commit any
        # page it completed.
        for req in running:
            pod.block_manager.mark_decode_computed(req.state)

        finished = []
        still_running = []
        for i, req in enumerate(running):
            if sampled_rows[i]:
                # Speculative sampling: accept while the min(1, q/p) draw
                # passes (capped by this row's budget); the first
                # rejection's residual draw — or the bonus/plain draw on
                # full acceptance — is the correction token.
                n_accept = 0
                correction = None
                for j in range(accepts[i]):
                    if bool(accept_oks[i, j]):
                        n_accept += 1
                    else:
                        correction = int(accept_toks[i, j])
                        break
                if correction is None:
                    correction = int(emit_draws[i, n_accept])
            else:
                # Greedy: a proposal is accepted while it matches the
                # target argmax chain, capped by this row's budget
                # (columns past accepts[i] exist only because the batch
                # is rectangular).
                n_accept = 0
                for j in range(accepts[i]):
                    if int(argmaxes[i, j]) != int(proposals[i, j]):
                        break
                    n_accept += 1
                correction = int(argmaxes[i, n_accept])
            self.stats.accepted += n_accept

            # Emit accepted proposals, then the correction token (which
            # becomes the next pending). decode_append is skipped for a
            # final token, matching the plain scheduler.
            to_emit = [int(p) for p in proposals[i, :n_accept]]
            to_emit.append(correction)
            done = False
            preempted = False
            for j, tok in enumerate(to_emit):
                req.generated.append(tok)
                if self.inner._done(req, tok):
                    done = True
                    break
                try:
                    pod.decode_append(req.state, tok)
                except OutOfPagesError:
                    self.inner._preempt(req)
                    preempted = True
                    break
                # Accepted proposals (every emitted token except the final
                # correction) already have device KV from the verify pass —
                # commit pages they complete. The correction token is the
                # new pending and stays uncommitted.
                if j < n_accept:
                    pod.block_manager.mark_decode_computed(req.state)
            if done:
                req.finished = True
                # Every token still in the sequence has resident KV (the
                # correction is only ever in `generated`, not appended on
                # the done path) — commit before freeing so the tail page
                # stays reusable in the prefix cache.
                pod.block_manager.mark_decode_computed(req.state)
                pod.free(req.state)
                self._release(req.req_id)
                finished.append(req)
                continue
            if preempted:
                self._release(req.req_id)  # rebuilt on re-admission
                continue
            # Draft validity: everything except the new pending token.
            self._draft_state[req.req_id][1] = len(req.state.tokens) - 1
            still_running.append(req)
        self.inner._running = still_running
        return finished

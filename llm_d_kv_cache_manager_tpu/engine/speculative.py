"""Greedy speculative decoding: draft proposes, target verifies in one pass.

TPU-first rationale: decode is bandwidth-bound (one token streams the whole
weight stack), but the MXU can score k+1 positions for nearly the price of
one. A small draft model proposes k tokens autoregressively; the target
then runs a single `prefill_cache(all_logits=True)` over the proposals —
one weight stream amortized over k positions — and accepts the longest
prefix whose greedy argmax chain matches. Every emitted token is the
argmax of TARGET logits, so the sampling rule is exactly target-only
greedy; the tests pin bit-identical output on f32 models. (In bf16 the
dense verify path and the paged decode path can round differently, so a
near-exact logit tie may resolve differently than plain decode would —
the same numerics caveat batched-vs-isolated decode already carries.)

Integration with the serving stack:
- the target sequence lives in the pod's real BlockManager: proposals'
  KV lands in pages reserved ahead (`reserve_pages`), and only ACCEPTED
  tokens are appended (so BlockStored events / prefix-cache commits never
  advertise unverified content). Rejected positions leave stale device
  rows beyond seq_len — masked by attention and overwritten by the next
  round, exactly like vLLM's rejected draft slots.
- the draft keeps a private paged cache (its own page pool, identity block
  table); after each round it catches up on the accepted tokens it did
  not itself propose.

Reference anchor: none (the reference executes no model math); vLLM's
speculative decoding is the behavioral anchor.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from llm_d_kv_cache_manager_tpu.engine.engine import EnginePod
from llm_d_kv_cache_manager_tpu.models import llama


@dataclass
class SpeculativeStats:
    proposed: int = 0
    accepted: int = 0
    rounds: int = 0

    @property
    def acceptance_rate(self) -> float:
        return self.accepted / self.proposed if self.proposed else 0.0


class _DraftState:
    """The draft model's private paged cache for one sequence."""

    def __init__(self, config, params, max_tokens: int, page_size: int):
        self.config = config
        self.params = params
        self.page_size = page_size
        n_pages = (max_tokens + page_size - 1) // page_size + 1
        self.cache = llama.make_kv_pages(config, n_pages, page_size)
        self.table = jnp.arange(n_pages, dtype=jnp.int32)
        self.n_tokens = 0  # positions with valid KV

    def ingest(self, tokens: List[int]) -> jax.Array:
        """Write KV for `tokens` at the current position; returns the
        last-position logits (the draft's next proposal seed). Single
        tokens ride the O(seq_len) paged decode path; multi-token catch-up
        chunks use prefill."""
        if len(tokens) == 1:
            self.cache, logits = llama.decode_step_cache(
                self.config, self.params, self.cache,
                jnp.asarray(tokens, jnp.int32),
                self.table[None],
                jnp.asarray([self.n_tokens], jnp.int32),
            )
            self.n_tokens += 1
            return logits[0]
        chunk = jnp.asarray(tokens, dtype=jnp.int32)
        self.cache, logits = llama.prefill_cache(
            self.config, self.params, self.cache, chunk, self.table,
            self.n_tokens,
        )
        self.n_tokens += len(tokens)
        return logits


class SpeculativeDecoder:
    """Single-sequence greedy generation with draft-model speculation."""

    def __init__(
        self,
        pod: EnginePod,
        draft_config,
        draft_params,
        k: int = 4,
    ):
        if pod._model is None:
            raise ValueError("SpeculativeDecoder requires with_model=True")
        if pod.lora_stack is not None:
            raise NotImplementedError("speculative decoding with LoRA adapters")
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        self.pod = pod
        self.draft_config = draft_config
        self.draft_params = draft_params
        self.k = k
        self.stats = SpeculativeStats()

    def generate(
        self,
        prompt_tokens: List[int],
        max_new_tokens: int,
        eos_token: Optional[int] = None,
    ) -> List[int]:
        pod = self.pod
        page_size = pod.config.page_size
        max_total = len(prompt_tokens) + max_new_tokens + self.k + 1

        state, _ = pod.prefill(list(prompt_tokens))
        draft = _DraftState(
            self.draft_config, self.draft_params, max_total, page_size
        )
        draft_logits = draft.ingest(list(prompt_tokens))

        generated: List[int] = []
        target_logits = pod.last_logits  # target's opinion at the frontier

        try:
            while len(generated) < max_new_tokens:
                # The frontier token: the target's own greedy choice.
                t0 = int(jnp.argmax(target_logits))
                pos_t0 = len(state.tokens)  # device position t0 will occupy

                # Cap proposals at what could possibly be accepted: the
                # remaining token budget after t0, and the sequence's page
                # capacity (reserving past max_pages_per_seq would crash a
                # generation that plain decode finishes fine).
                capacity_tokens = (
                    pod.config.max_pages_per_seq * page_size - pos_t0 - 1
                )
                k_eff = max(
                    0,
                    min(self.k, max_new_tokens - len(generated) - 1,
                        capacity_tokens),
                )

                # Draft proposes k_eff tokens after t0 (greedy,
                # autoregressive). In the final stretch (k_eff == 0) the
                # draft is skipped entirely — no further rounds propose.
                proposals: List[int] = []
                if k_eff > 0:
                    seed_logits = draft.ingest([t0])
                    for _ in range(k_eff):
                        p = int(jnp.argmax(seed_logits))
                        proposals.append(p)
                        seed_logits = draft.ingest([p])
                self.stats.proposed += len(proposals)
                self.stats.rounds += 1

                # Target verifies all proposals in ONE pass. The chunk
                # starts with t0 (whose KV is not yet in the cache);
                # logits[i] is the target's next-token opinion after
                # chunk[i], so logits[i] vs proposals[i] is the acceptance
                # test and logits[n_accept] seeds the next round.
                chunk = [t0] + proposals
                pod.block_manager.reserve_pages(
                    state,
                    (pos_t0 + len(chunk) + page_size - 1) // page_size,
                )
                pod.kv_cache, verify_logits = llama.prefill_cache(
                    pod._model_config, pod.params, pod.kv_cache,
                    jnp.asarray(chunk, jnp.int32),
                    pod._padded_table(state), pos_t0,
                    all_logits=True,
                )
                argmaxes = np.asarray(jnp.argmax(verify_logits, axis=-1))

                n_accept = 0
                for i, p in enumerate(proposals):
                    if int(argmaxes[i]) != p:
                        break
                    n_accept += 1
                self.stats.accepted += n_accept

                done = False
                for tok in [t0] + proposals[:n_accept]:
                    if self._push(state, generated, tok, eos_token,
                                  max_new_tokens):
                        done = True
                        break
                if done:
                    break

                # Draft already holds KV for t0 + all proposals; on partial
                # acceptance its tail (like the target's) is stale-but-
                # masked. Rewind its valid-token count to the accepted
                # frontier so the next ingest overwrites the stale rows.
                # (k_eff == 0 rounds never touched the draft — and k_eff is
                # monotonic, so it stays untouched.)
                if k_eff > 0:
                    draft.n_tokens = len(state.tokens)
                target_logits = verify_logits[n_accept]
        finally:
            pod.free(state)
        return generated

    def _push(
        self, state, generated: List[int], token: int,
        eos_token: Optional[int], max_new_tokens: int,
    ) -> bool:
        """Append one ACCEPTED token to the real sequence (block-manager
        accounting + events). Returns True when generation is finished."""
        generated.append(token)
        if eos_token is not None and token == eos_token:
            return True
        if len(generated) >= max_new_tokens:
            return True
        self.pod.block_manager.append_token(state, token)
        return False

"""EnginePod: a minimal vLLM-TPU-style serving pod.

Ties together the device path (models/llama.py + ops/paged_attention.py) and
the host path (engine/block_manager.py), publishing the same KVEvents wire
traffic a real vLLM-TPU engine would (kvevents/publisher.py) so the control
plane can index it. Used three ways:

- e2e tests: two pods + an Indexer, verifying scores follow real cache state,
- bench.py: fleet simulation (accounting-only mode, no model compute),
- examples: live demo engines.

Accounting-only mode (`with_model=False`) runs the full block-manager +
event path without device compute; model mode runs real prefill/decode with
the paged cache on whatever backend JAX has.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple

import numpy as np

from llm_d_kv_cache_manager_tpu.engine.block_manager import (
    BlockManager,
    BlockManagerConfig,
    OutOfPagesError,
    SequenceState,
)
from llm_d_kv_cache_manager_tpu.engine.tiering import PageCodec
from llm_d_kv_cache_manager_tpu.kvevents.events import EventBatch
from llm_d_kv_cache_manager_tpu.kvevents.publisher import Publisher, make_topic
from llm_d_kv_cache_manager_tpu.utils import logging as kvlog

logger = kvlog.get_logger("engine")

_GATHER_PAGES = None
_SCATTER_PAGES = None


def _gather_pages(cache: tuple, page_ids):
    """Jitted gather of N pages from every cache component in ONE dispatch.

    Returns one [N, n_layers, n_kv, ...] array per component; each row's
    C-order bytes are exactly the per-page payload slice. One dispatch per
    (cache-shape, N-bucket) pair — on a tunneled chip every eager op is an
    RPC, so the per-component/per-page slicing this replaces paid
    O(components x pages) round trips per batch."""
    global _GATHER_PAGES
    if _GATHER_PAGES is None:
        import jax

        _GATHER_PAGES = jax.jit(
            lambda c, ids: tuple(
                jax.numpy.moveaxis(comp[:, :, ids], 2, 0) for comp in c
            )
        )
    return _GATHER_PAGES(cache, page_ids)


def _scatter_pages(cache: tuple, page_ids, blocks: tuple):
    """Jitted, donating write of N page payloads into every component in ONE
    dispatch: comp[:, :, page_ids[n]] = blocks[comp][n] for all n."""
    global _SCATTER_PAGES
    if _SCATTER_PAGES is None:
        import jax

        _SCATTER_PAGES = jax.jit(
            lambda c, ids, bs: tuple(
                comp.at[:, :, ids].set(jax.numpy.moveaxis(b, 0, 2))
                for comp, b in zip(c, bs)
            ),
            donate_argnums=(0,),
        )
    return _SCATTER_PAGES(cache, page_ids, blocks)


def _pad_bucket(n: int) -> int:
    """Power-of-2 page-count bucket so the gather/scatter jits compile O(log)
    programs, not one per batch size."""
    bucket = 1
    while bucket < n:
        bucket *= 2
    return bucket


class _DevicePageCodec(PageCodec):
    """Serializes logical pages across every layer of the pod's KV cache.

    Works for both layouts (bf16 (k, v) pair and int8 quantized 4-tuple):
    each cache component is [n_layers, n_kv_heads, n_pages, page_size, ...]
    with the page axis at position 2, so a block's bytes are the
    concatenation of each component's [:, :, page_id] slice.

    All device crossings are batched: extract_many/insert_many move N pages
    in one jitted dispatch + one transfer (the single-page forms are the
    N=1 case). The reference plans this data plane but never builds it
    (kv_connectors/ is empty); on TPU the batching is the difference
    between O(pages) and O(1) host round trips per restored prefix chain.
    """

    def __init__(self, pod: "EnginePod"):
        self.pod = pod

    @staticmethod
    def _slice_shape(comp) -> tuple:
        return comp.shape[:2] + comp.shape[3:]

    @staticmethod
    def _slice_nbytes(comp) -> int:
        return int(np.prod(_DevicePageCodec._slice_shape(comp))) * np.dtype(
            comp.dtype
        ).itemsize

    @property
    def page_nbytes(self) -> int:
        return sum(self._slice_nbytes(c) for c in self.pod.kv_cache)

    def extract(self, page_id: int) -> bytes:
        return self.extract_many([page_id])[0]

    def extract_many(self, page_ids) -> List[bytes]:
        # The async form with an immediate resolve — one gather dispatch,
        # one code path for padding + serialization.
        return self.extract_many_async(page_ids)()

    def extract_many_async(self, page_ids):
        """Snapshot pages for background staging: the gather dispatch and
        the device→host copy start NOW (enqueued behind whatever compute is
        already queued, so the transfer overlaps it), and resolve() pays
        only the residual sync. The gather consumes kv_cache in enqueue
        order, so a later scatter/donation reusing these pages cannot
        corrupt the snapshot."""
        import jax
        import jax.numpy as jnp

        ids = list(page_ids)
        if not ids:
            return lambda: []
        n = len(ids)
        bucket = _pad_bucket(n)
        padded = np.asarray(ids + [ids[-1]] * (bucket - n), dtype=np.int32)
        parts = _gather_pages(self.pod.kv_cache, jnp.asarray(padded))
        for p in parts:
            try:
                p.copy_to_host_async()
            except Exception:  # noqa: BLE001 - a hint; device_get still works
                pass

        def resolve():
            host = jax.device_get(parts)
            return [
                b"".join(np.ascontiguousarray(p[i]).tobytes() for p in host)
                for i in range(n)
            ]

        return resolve

    def insert(self, page_id: int, payload: bytes) -> None:
        self.insert_many([(page_id, payload)])

    def insert_many(self, items) -> None:
        import jax.numpy as jnp

        if not items:
            return
        for _, payload in items:
            if len(payload) != self.page_nbytes:
                raise ValueError(
                    f"block payload is {len(payload)} bytes, expected "
                    f"{self.page_nbytes}"
                )
        n = len(items)
        bucket = _pad_bucket(n)
        # Pad with a repeat of the last item: duplicate scatter indices
        # write identical content, so the pad rows are harmless.
        padded = list(items) + [items[-1]] * (bucket - n)
        ids = np.asarray([pid for pid, _ in padded], dtype=np.int32)
        blocks = []
        for ci, comp in enumerate(self.pod.kv_cache):
            nbytes = self._slice_nbytes(comp)
            offset = sum(
                self._slice_nbytes(c) for c in self.pod.kv_cache[:ci]
            )
            blocks.append(
                np.stack(
                    [
                        np.frombuffer(
                            payload[offset:offset + nbytes], dtype=comp.dtype
                        ).reshape(self._slice_shape(comp))
                        for _, payload in padded
                    ]
                )
            )
        self.pod.kv_cache = _scatter_pages(
            self.pod.kv_cache, jnp.asarray(ids), tuple(blocks)
        )


@dataclass
class EnginePodConfig:
    pod_id: str = "pod-0"
    model_name: str = "test-model"
    zmq_endpoint: Optional[str] = None  # None -> direct event_sink only
    n_pages: int = 512
    page_size: int = 16
    hash_seed: str = ""
    device_tier: Optional[str] = None
    max_pages_per_seq: int = 32
    with_model: bool = False
    model_config: Optional[object] = None  # models.llama.LlamaConfig
    # int8 KV pages: half the HBM per cached token -> double the prefixes a
    # pod can keep resident (ops/quantized_kv.py).
    use_quantized_kv: bool = False
    # Decode through the Pallas flash-decoding kernel (True on TPU; the jnp
    # oracle path works on any backend and is the test default).
    use_kernel: bool = False
    # Tensor parallelism over the pod's slice: weights Megatron-sharded and
    # KV pages head-sharded over a tp-device mesh (parallel/serving.py).
    # The pod remains ONE pod to the control plane — block tables, events,
    # and the block manager are tp-invariant host state. tp=1 -> no mesh.
    tp: int = 1
    # Two-tier data plane (engine/tiering.py): reclaimed HBM pages offload
    # to the C++ host staging store instead of vanishing, and allocation
    # misses restore from host / onboard from peer pods over DCN.
    enable_host_tier: bool = False
    host_capacity_blocks: int = 1024
    transfer_port: int = 0  # 0 -> ephemeral
    # Transfer-vs-recompute gate (engine/costs.py). "auto": model pods gate
    # with a cost model seeded from this model's arithmetic intensity x the
    # rig's measured rates (DEVICE_BENCH.json when present); accounting-only
    # pods (no model: zero-byte payloads) run ungated. Pass an explicit
    # TransferCostModel (e.g. costs.ALWAYS_TRANSFER) to override, or None
    # to disable gating.
    transfer_cost_model: object = "auto"
    # Ready-buffer bound for the async payload prefetcher (blocks held in
    # host RAM awaiting their device insert); <=0 disables prefetch.
    prefetch_capacity_blocks: int = 64
    # Eager staging: free() snapshots the sequence's committed pages (one
    # enqueued gather whose host copy overlaps queued compute) and a
    # background thread admits them to the host store — so a later reclaim
    # finds them resident instead of paying a synchronous extract on the
    # allocation path (VERDICT r4 #7 overlap lever). Off by default:
    # free-then-rehit workloads would snapshot pages that never evict.
    eager_stage: bool = False
    # Bound on un-resolved eager snapshots (their gather outputs hold HBM
    # until the background admit lands); blocks past the budget fall back
    # to the synchronous reclaim-time stage.
    async_stage_capacity_pages: int = 128
    # Transfer-plane pipelining (engine/tiering.py + kv_connectors): pages
    # per extract wave in the double-buffered stager, blocks per H2D
    # insert wave during chain onboard (each wave overlaps the next
    # network receive), and blocks per multi-block DCN round trip.
    stage_wave_pages: int = 16
    onboard_wave_blocks: int = 8
    fetch_batch_blocks: int = 32
    # DCN client bounds: a dead peer costs at most
    # connect/fetch timeout x (retries+1) per chain, then degrades to a
    # cache miss (counted in the transfer_failures metric).
    transfer_connect_timeout_ms: int = 2000
    transfer_fetch_timeout_ms: int = 5000
    transfer_fetch_retries: int = 1


class EnginePod:
    def __init__(
        self,
        config: EnginePodConfig,
        event_sink: Optional[Callable[[EventBatch], None]] = None,
        params=None,
        lora_adapters: Optional[dict] = None,  # {lora_id: models.lora params}
    ):
        self.config = config
        self._publisher: Optional[Publisher] = None
        if config.zmq_endpoint:
            self._publisher = Publisher(
                config.zmq_endpoint, make_topic(config.pod_id, config.model_name)
            )
        self._extra_sink = event_sink

        self.tier_store = None
        self.connector = None
        if config.enable_host_tier:
            from llm_d_kv_cache_manager_tpu.kv_connectors.connector import (
                KVConnector,
                KVConnectorConfig,
            )
            from llm_d_kv_cache_manager_tpu.engine.tiering import (
                NullPageCodec,
                TieredKVStore,
            )

            self.connector = KVConnector(
                KVConnectorConfig(
                    port=config.transfer_port,
                    connect_timeout_ms=config.transfer_connect_timeout_ms,
                    fetch_timeout_ms=config.transfer_fetch_timeout_ms,
                    fetch_retries=config.transfer_fetch_retries,
                    fetch_batch_size=config.fetch_batch_blocks,
                ),
                event_sink=self._emit,
            )
            codec = (
                _DevicePageCodec(self) if config.with_model else NullPageCodec()
            )
            # "auto" for a model pod resolves below once the model config is
            # known; accounting-only pods stay ungated (zero-byte payloads
            # cost nothing to move).
            cost_model = (
                None
                if config.transfer_cost_model == "auto"
                else config.transfer_cost_model
            )
            self.tier_store = TieredKVStore(
                self.connector, codec,
                capacity_blocks=config.host_capacity_blocks,
                cost_model=cost_model,
                prefetch_capacity_blocks=config.prefetch_capacity_blocks,
                async_stage_capacity_pages=config.async_stage_capacity_pages,
                stage_wave_pages=config.stage_wave_pages,
                onboard_wave_blocks=config.onboard_wave_blocks,
                fetch_batch_blocks=config.fetch_batch_blocks,
            )

        self.block_manager = BlockManager(
            BlockManagerConfig(
                n_pages=config.n_pages,
                page_size=config.page_size,
                hash_seed=config.hash_seed,
                device_tier=config.device_tier,
            ),
            event_sink=self._emit,
            reclaim_hook=self.tier_store.reclaim_hook if self.tier_store else None,
            page_loader=self.tier_store.page_loader if self.tier_store else None,
            reclaim_many_hook=(
                self.tier_store.reclaim_many_hook if self.tier_store else None
            ),
            chain_planner=(
                self.tier_store.plan_restore if self.tier_store else None
            ),
            chain_loader=(
                self.tier_store.load_chain if self.tier_store else None
            ),
        )

        self._model = None
        if config.with_model:
            import jax
            import jax.numpy as jnp

            from llm_d_kv_cache_manager_tpu.models import llama

            mc = config.model_config or llama.LlamaConfig()
            # Both model families serve through llama.py's paged ops (the
            # MLP dispatches on the layer dict's structure): a config
            # carrying n_experts is the MoE family (models/mixtral.py).
            self._model = llama
            self._model_config = mc
            if (
                self.tier_store is not None
                and config.transfer_cost_model == "auto"
            ):
                from llm_d_kv_cache_manager_tpu.engine.costs import (
                    TransferCostModel,
                )

                self.tier_store.cost_model = TransferCostModel.for_model(
                    mc, quantized=config.use_quantized_kv
                )
            # Sliding-window checkpoints (HF Mistral defaults to 4096) are
            # served exactly: every attention path masks to the window
            # (models/llama.py _dense_attention + ops paged kernels, which
            # also skip out-of-window page DMAs in the pipelined variant).
            if config.tp > 1 and llama.is_moe_config(mc):
                # Reject BEFORE params init / page allocation: a real-size
                # MoE pod would otherwise build GB-scale expert weights
                # just to throw them away.
                raise NotImplementedError(
                    "tp serving for the MoE family needs an expert "
                    "sharding spec set (parallel/serving.py covers the "
                    "dense family); run MoE pods at tp=1"
                )
            if params is None:
                if llama.is_moe_config(mc):
                    from llm_d_kv_cache_manager_tpu.models import mixtral

                    params = mixtral.init_params(mc, jax.random.PRNGKey(0))
                else:
                    params = llama.init_params(mc, jax.random.PRNGKey(0))
            if llama.is_moe_config(mc) != ("router" in params["layers"]):
                raise ValueError(
                    "model_config family does not match params structure "
                    "(MoE config needs router/expert params and vice versa)"
                )
            self.params = params
            # One sacrificial page beyond the block manager's pool: the
            # multi-step decode loop steers per-sequence out-of-budget KV
            # writes there (models/llama.decode_multi_step_cache), so a
            # rectangular batch can keep stepping past a short sequence's
            # capacity without corrupting real pages. Never referenced by
            # any block table.
            self.trash_page = config.n_pages
            if config.use_quantized_kv:
                self.kv_cache = llama.make_kv_pages_quantized(
                    mc, config.n_pages + 1, config.page_size
                )
            else:
                self.kv_cache = llama.make_kv_pages(
                    mc, config.n_pages + 1, config.page_size
                )
            self.mesh = None
            if config.tp > 1:
                from llm_d_kv_cache_manager_tpu.parallel import serving

                serving.validate_tp(config.tp, mc.n_q_heads, mc.n_kv_heads)
                self.mesh = serving.tp_mesh(config.tp)
                self.params = serving.shard_serving_params(self.params, self.mesh)
                self.kv_cache = serving.shard_kv_cache(self.kv_cache, self.mesh)
            self._jnp = jnp

        # Multi-LoRA registry: adapter weights served per sequence, with
        # the cache already scoped per adapter (block hashes carry
        # lora_id). Index 0 is the zero adapter (base traffic).
        self.lora_stack = None
        self._lora_index: dict = {}
        if lora_adapters:
            if self._model is None:
                raise ValueError("lora_adapters requires with_model=True")
            from llm_d_kv_cache_manager_tpu.models import lora as lora_mod

            ids = sorted(lora_adapters)
            self.lora_stack = lora_mod.stack_adapters(
                [lora_adapters[i] for i in ids]
            )
            self._lora_index = {lid: i + 1 for i, lid in enumerate(ids)}

    # -- events --------------------------------------------------------------

    def _emit(self, batch: EventBatch) -> None:
        if self._publisher is not None:
            self._publisher.publish(batch)
        if self._extra_sink is not None:
            self._extra_sink(batch)

    # -- serving -------------------------------------------------------------

    def prefill(
        self, tokens: List[int], lora_id: Optional[int] = None
    ) -> Tuple[SequenceState, int]:
        """Admit a sequence: allocate (with prefix reuse), compute the
        uncached suffix in one chunk, commit pages + events. Returns
        (state, cached_tokens). Chunked admission goes through
        begin_prefill/prefill_chunk/finish_prefill instead."""
        state, start = self.begin_prefill(tokens, lora_id=lora_id)
        self.prefill_chunk(state, start, len(tokens))
        self.finish_prefill(state)
        return state, state.num_cached_tokens

    # -- chunked prefill (scheduler drives these; `prefill` = one-shot) ------

    def begin_prefill(
        self, tokens: List[int], lora_id: Optional[int] = None
    ) -> Tuple[SequenceState, int]:
        """Allocate pages (with prefix reuse) without computing anything.
        Follow with prefill_chunk over [n_cached, len(tokens)) in any chunk
        sizes, then finish_prefill. Returns (state, compute_start): the
        position chunked compute must start from (== num_cached_tokens,
        except fully-cached prompts where the last position is recomputed
        for logits)."""
        state = self.block_manager.allocate(tokens, lora_id=lora_id)
        n_cached = state.num_cached_tokens
        if n_cached >= len(tokens):
            n_cached = min(n_cached, len(tokens) - 1)
        return state, n_cached

    def lora_index(self, lora_id: Optional[int]) -> int:
        """Registry index for an adapter id (0 = base). Raises KeyError for
        an unknown adapter so admission can reject deterministically."""
        if lora_id is None:
            return 0
        if self.lora_stack is None:
            raise KeyError(f"no LoRA adapters configured (requested {lora_id})")
        return self._lora_index[lora_id]

    def _lora_for_prefill(self, lora_id: Optional[int]):
        if self.lora_stack is None:
            return None
        from llm_d_kv_cache_manager_tpu.models import lora as lora_mod

        return lora_mod.select_adapter(self.lora_stack, self.lora_index(lora_id))

    def lora_for_decode(self, lora_ids):
        """(registry stack, [B] indices) for a decode batch, or None when
        the pod serves no adapters. The per-sequence weight gather happens
        inside the jitted step, not here."""
        if self.lora_stack is None:
            return None
        import numpy as _np

        idx = self._jnp.asarray(
            _np.asarray([self.lora_index(i) for i in lora_ids], dtype=_np.int32)
        )
        return (self.lora_stack, idx)

    def prefill_chunk(self, state: SequenceState, start: int, end: int) -> None:
        """Compute KV (and logits) for tokens[start:end], attending over the
        first `start` already-resident positions. vLLM-style chunked
        prefill: the scheduler bounds end-start by its token budget so
        decode ticks interleave with long prompts.

        The chunk is padded to a power-of-2 length bucket so XLA compiles
        one program per (bucket, table-bucket) pair instead of one per
        prompt length — on TPU a compile costs seconds, so per-length
        compilation would dominate a live fleet's TTFT. Pad rows write
        garbage KV into reserved-ahead pages at positions beyond `end`;
        every later real write lands at its position before that position
        is ever attended, and page commits only ever cover real computed
        tokens, so the garbage is never advertised or read."""
        if self._model is None:
            return  # accounting-only pods have no compute to chunk
        jnp = self._jnp
        length = end - start
        bucket = self.batch_bucket(length)
        if bucket > length:
            from llm_d_kv_cache_manager_tpu.engine.block_manager import (
                OutOfPagesError,
            )

            ps = self.config.page_size
            pages_needed = (start + bucket + ps - 1) // ps
            if pages_needed > self.config.max_pages_per_seq:
                bucket = length  # capacity-capped: compute unpadded
            else:
                try:
                    self.block_manager.reserve_pages(state, pages_needed)
                except OutOfPagesError:
                    bucket = length  # pool too tight: compute unpadded
        block_table = self._padded_table(state)
        chunk_tokens = state.tokens[start:end] + [0] * (bucket - length)
        chunk = jnp.asarray(chunk_tokens, dtype=jnp.int32)
        # n_valid is passed even when the chunk is exactly bucket-sized:
        # a None/array split would compile TWO programs per bucket pair.
        self.kv_cache, self.last_logits = self._model.prefill_cache(
            self._model_config, self.params, self.kv_cache, chunk,
            block_table, start, lora=self._lora_for_prefill(state.lora_id),
            n_valid=jnp.asarray(length, jnp.int32),
        )

    def prefill_chunk_batch(self, jobs):
        """Compute several sequences' prefill chunks in ONE dispatch.

        `jobs`: [(state, start, end)] — each sequence's tokens[start:end)
        computed while attending its own cached prefix. Returns one
        last-position logits vector per job (None-padded rows excluded).

        This is packed prefill: a tick admitting several prompts pays one
        weight stream instead of one per prompt. The op is
        `verify_step_cache` — batched multi-position KV+logits with
        per-sequence causal offsets — with per-sequence `max_lens` steering
        the rectangular batch's pad-tail rows into the trash page, so no
        page reservation beyond each sequence's real tokens is needed.
        Single-job ticks ride the single-sequence `prefill_chunk` (its
        length-bucketed program is cheaper than the batched gather).
        """
        if len(jobs) == 1:
            state, start, end = jobs[0]
            self.prefill_chunk(state, start, end)
            return [self.last_logits]
        jnp = self._jnp
        lengths = [end - start for _, start, end in jobs]
        l_bucket = self.batch_bucket(max(lengths))
        b_pad = self.batch_bucket(len(jobs))
        # Skew guard: a rectangular batch pays bucket-width compute for
        # every row. When padding more than doubles the real token count
        # (e.g. three 1-token chunks packed beside a 256-token slice),
        # per-sequence length-bucketed dispatches are the cheaper shape.
        if b_pad * l_bucket > 2 * sum(lengths):
            out = []
            for state, start, end in jobs:
                self.prefill_chunk(state, start, end)
                out.append(self.last_logits)
            return out
        t_bucket = self.table_bucket(
            max(len(state.block_table) for state, _, _ in jobs)
        )
        chunk = np.zeros((b_pad, l_bucket), dtype=np.int32)
        tables = np.full((b_pad, t_bucket), self.trash_page, dtype=np.int32)
        starts = np.zeros((b_pad,), dtype=np.int32)
        max_lens = np.zeros((b_pad,), dtype=np.int32)  # pad rows all-trash
        for i, (state, start, end) in enumerate(jobs):
            chunk[i, : end - start] = state.tokens[start:end]
            tables[i, : len(state.block_table)] = state.block_table
            starts[i] = start
            max_lens[i] = end  # real rows: positions start .. end-1
        lora_ids = [state.lora_id for state, _, _ in jobs]
        lora_ids += [None] * (b_pad - len(jobs))
        self.kv_cache, logits = self._model.verify_step_cache(
            self._model_config, self.params, self.kv_cache,
            jnp.asarray(chunk), jnp.asarray(tables), jnp.asarray(starts),
            jnp.asarray(max_lens), self.trash_page,
            lora=self.lora_for_decode(lora_ids),
        )
        return [logits[i, lengths[i] - 1] for i in range(len(jobs))]

    def finish_prefill(self, state: SequenceState) -> None:
        """Commit full pages + emit BlockStored — only now, when every
        page's KV is actually computed; advertising blocks mid-prefill would
        let peers onboard garbage."""
        self.block_manager.commit_prefill(state)

    def decode_append(self, state: SequenceState, token: int) -> None:
        """Record one generated token. For accounting-only pods the token
        counts as computed immediately (there is no device KV whose residency
        could lag); model pods leave it pending until the next device pass
        calls mark_decode_computed."""
        self.block_manager.append_token(state, token)
        if self._model is None:
            self.block_manager.mark_decode_computed(state)

    def decode_step(self, state: SequenceState) -> int:
        """Model decode: greedy-sample one token for this sequence."""
        if self._model is None:
            raise RuntimeError("decode_step requires with_model=True")
        jnp = self._jnp
        pos = len(state.tokens) - 1
        last_token = jnp.asarray([state.tokens[-1]], dtype=jnp.int32)
        # The last token's K/V were already written by prefill/previous step;
        # decode_step writes at seq_lens, so pass position of the new token.
        self.kv_cache, logits = self._model.decode_step_cache(
            self._model_config,
            self.params,
            self.kv_cache,
            last_token,
            self._padded_table(state)[None],
            jnp.asarray([pos], dtype=jnp.int32),
            self.config.use_kernel,
            lora=self.lora_for_decode([state.lora_id]),
        )
        # The pending token's KV row is now device-resident: commit any page
        # it completed before appending the next (pending) token.
        self.block_manager.mark_decode_computed(state)
        token = int(jnp.argmax(logits[0]))
        self.block_manager.append_token(state, token)
        return token

    def free(self, state: SequenceState) -> None:
        if (
            self.tier_store is not None
            and self.config.eager_stage
            and self.config.with_model
        ):
            # Snapshot while the pages are still committed; the gather is
            # enqueued on this (serving) thread, so it precedes any later
            # allocation's overwrite in device order. Best-effort: a
            # snapshot failure (e.g. OOM allocating gather outputs under
            # the very pressure that triggered the free) must never leak
            # the sequence's pages — the blocks just fall back to the
            # synchronous reclaim-time stage.
            try:
                self.tier_store.stage_async(
                    list(self.block_manager.committed_blocks(state))
                )
            except Exception as e:  # noqa: BLE001 - staging is best-effort
                logger.debug("eager stage snapshot failed on free: %s", e)
        self.block_manager.free(state)

    # -- data plane -----------------------------------------------------------

    @property
    def transfer_address(self) -> Optional[Tuple[str, int]]:
        """(host, port) peers use to fetch this pod's staged blocks."""
        if self.connector is None:
            return None
        return ("127.0.0.1", self.connector.port)

    def set_peer_resolver(self, resolver) -> None:
        """Install the hash→peer-address resolver (after the fleet's pods and
        shared index exist — see tiering.IndexBackedPeerResolver)."""
        if self.tier_store is None:
            raise RuntimeError("enable_host_tier=False: no data plane to configure")
        self.tier_store.peer_resolver = resolver

    def export_sequence(self, state: SequenceState) -> int:
        """Stage every committed page of a live sequence in the transfer
        server (pages stay in HBM) so peers can onboard them — the
        prefill/decode-disaggregation push. Returns the number staged."""
        if self.tier_store is None:
            raise RuntimeError("enable_host_tier=False: no data plane to export to")
        blocks = list(self.block_manager.committed_blocks(state))
        self.tier_store.export_blocks(blocks)
        return len(blocks)

    def prefetch(self, tokens: List[int], lora_id: Optional[int] = None) -> int:
        """Start background payload fetches for this prompt's restorable
        blocks (announced-but-not-yet-admitted requests: the fetch rides
        the queue wait instead of the TTFT critical path). No-op without a
        data plane. Returns the number of fetches queued."""
        if self.tier_store is None:
            return 0
        keys = self.block_manager.token_db.tokens_to_kv_block_keys(
            None, [int(t) for t in tokens], "", lora_id=lora_id
        )
        return self.prefetch_hashes([k.chunk_hash for k in keys])

    def prefetch_hashes(self, chunk_hashes: List[int]) -> int:
        """Route-driven prefetch entry point: the router already derived
        this prompt's chain and knows which tail this pod misses
        (Indexer.get_pod_scores_ex → PodScores.missing_tail), so no
        re-derivation happens here — just an HBM-residency filter and the
        background fetch queue. Returns the number of fetches queued."""
        if self.tier_store is None:
            return 0
        missing = [
            h for h in chunk_hashes if not self.block_manager.is_cached(h)
        ]
        return self.tier_store.prefetch(missing)

    def resident_prefix_blocks(self, chunk_hashes: List[int]) -> int:
        """Length of the leading run of `chunk_hashes` whose blocks are
        resident in this pod's device cache RIGHT NOW. The anticipate
        bench's audit seam: called at arrival time, before admission, it
        answers "was the predicted continuation prefix fully pre-landed
        before the request showed up?" — prefill would make the blocks
        resident and erase the evidence."""
        n = 0
        for h in chunk_hashes:
            if not self.block_manager.is_cached(h):
                break
            n += 1
        return n

    def resident_block_digest(
        self,
        device_hashes: List[int] = (),
        host_hashes: List[int] = (),
        max_extra: int = 0,
    ) -> dict:
        """Compact resident-set digest — the anti-entropy audit challenge
        surface (antientropy/auditor.py). Answers, per tier family:
        which of the CHALLENGED hashes are resident right now (`device`
        against the block manager's committed cache — the same membership
        `resident_prefix_blocks` walks — and `host` against the staged
        store, the fetchable tier), plus bounded `extra_*` samples of
        resident hashes for the re-admit direction. Membership checks
        only: no bytes move, no pages allocate, so a pod can answer this
        on every audit round for free. The sim and a real pod's sidecar
        expose the same dict over their respective transports."""
        out = {
            "device": {
                h for h in device_hashes if self.block_manager.is_cached(h)
            },
            "host": set(),
            "extra_device": [],
            "extra_host": [],
        }
        if self.tier_store is not None:
            out["host"] = self.tier_store.staged_subset(host_hashes)
            if max_extra > 0:
                out["extra_host"] = self.tier_store.staged_sample(max_extra)
        if max_extra > 0:
            out["extra_device"] = self.block_manager.cached_hashes(max_extra)
        return out

    def warm_chain(self, tokens: List[int], lora_id: Optional[int] = None) -> int:
        """Replication warm admission (placement/): materialize the longest
        *restorable* prefix of this token chain through the data plane
        (ready buffer → host store → peers over DCN), commit it as cached
        blocks — `_try_load_chain` emits the chained BlockStored, so the
        fleet index learns the new replica — and release the pages back to
        the evictable prefix cache. Never computes: blocks no tier can
        supply are simply not admitted (a replication hint must not burn
        MXU time on speculation), and already-resident blocks cost nothing
        (idempotent re-warm). Returns the number of blocks newly landed."""
        if self.tier_store is None:
            return 0
        tokens = [int(t) for t in tokens]
        ps = self.config.page_size
        keys = self.block_manager.token_db.tokens_to_kv_block_keys(
            None, tokens, "", lora_id=lora_id
        )
        if not keys:
            return 0
        n_resident = 0
        for key in keys:
            if not self.block_manager.is_cached(key.chunk_hash):
                break
            n_resident += 1
        rest = [k.chunk_hash for k in keys[n_resident:]]
        if not rest:
            return 0
        restorable = self.tier_store.plan_restore(rest)
        if restorable <= 0:
            return 0
        n_blocks = n_resident + restorable
        try:
            state = self.block_manager.allocate(
                tokens[: n_blocks * ps], lora_id=lora_id
            )
        except OutOfPagesError:
            return 0  # pressure wins: replication never preempts serving
        landed = max(state.num_cached_tokens // ps - n_resident, 0)
        self.block_manager.free(state)
        return landed

    def close(self) -> None:
        if self._publisher is not None:
            self._publisher.close()
        if self.tier_store is not None:
            self.tier_store.close()
        if self.connector is not None:
            self.connector.close()

    # -- helpers -------------------------------------------------------------

    @staticmethod
    def batch_bucket(n: int) -> int:
        """Next power-of-2 shape bucket (>=1). Batch axes of decode/verify
        dispatches and prefill chunk lengths all pad to this, so XLA
        compiles O(log) programs per axis instead of one per distinct
        size. The ONE definition every padded axis uses."""
        bucket = 1
        while bucket < n:
            bucket *= 2
        return bucket

    def table_bucket(self, n_pages_needed: int) -> int:
        """Padded block-table width: next power of two covering the need, so
        short prompts don't pay attention compute over the maximal static
        shape; jit specializes per bucket. Single source of truth for both
        single-sequence and scheduler-batched decode shapes."""
        if n_pages_needed > self.config.max_pages_per_seq:
            raise ValueError(
                f"sequence needs {n_pages_needed} pages > "
                f"max_pages_per_seq={self.config.max_pages_per_seq}; truncating "
                "would silently corrupt K/V pages"
            )
        bucket = 1
        while bucket < max(n_pages_needed, 1):
            bucket *= 2
        return min(bucket, self.config.max_pages_per_seq)

    def _padded_table(self, state: SequenceState):
        bucket = self.table_bucket(len(state.block_table))
        jnp_or_np = self._jnp if self._model is not None else np
        table = np.zeros((bucket,), dtype=np.int32)
        table[: len(state.block_table)] = state.block_table
        return jnp_or_np.asarray(table)

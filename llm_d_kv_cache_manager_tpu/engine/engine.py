"""EnginePod: a minimal vLLM-TPU-style serving pod.

Ties together the device path (models/llama.py + ops/paged_attention.py) and
the host path (engine/block_manager.py), publishing the same KVEvents wire
traffic a real vLLM-TPU engine would (kvevents/publisher.py) so the control
plane can index it. Used three ways:

- e2e tests: two pods + an Indexer, verifying scores follow real cache state,
- bench.py: fleet simulation (accounting-only mode, no model compute),
- examples: live demo engines.

Accounting-only mode (`with_model=False`) runs the full block-manager +
event path without device compute; model mode runs real prefill/decode with
the paged cache on whatever backend JAX has.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple

import numpy as np

from llm_d_kv_cache_manager_tpu.engine.block_manager import (
    BlockManager,
    BlockManagerConfig,
    SequenceState,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import EventBatch
from llm_d_kv_cache_manager_tpu.kvevents.publisher import Publisher, make_topic


@dataclass
class EnginePodConfig:
    pod_id: str = "pod-0"
    model_name: str = "test-model"
    zmq_endpoint: Optional[str] = None  # None -> direct event_sink only
    n_pages: int = 512
    page_size: int = 16
    hash_seed: str = ""
    device_tier: Optional[str] = None
    max_pages_per_seq: int = 32
    with_model: bool = False
    model_config: Optional[object] = None  # models.llama.LlamaConfig
    # int8 KV pages: half the HBM per cached token -> double the prefixes a
    # pod can keep resident (ops/quantized_kv.py).
    use_quantized_kv: bool = False
    # Decode through the Pallas flash-decoding kernel (True on TPU; the jnp
    # oracle path works on any backend and is the test default).
    use_kernel: bool = False


class EnginePod:
    def __init__(
        self,
        config: EnginePodConfig,
        event_sink: Optional[Callable[[EventBatch], None]] = None,
        params=None,
    ):
        self.config = config
        self._publisher: Optional[Publisher] = None
        if config.zmq_endpoint:
            self._publisher = Publisher(
                config.zmq_endpoint, make_topic(config.pod_id, config.model_name)
            )
        self._extra_sink = event_sink

        self.block_manager = BlockManager(
            BlockManagerConfig(
                n_pages=config.n_pages,
                page_size=config.page_size,
                hash_seed=config.hash_seed,
                device_tier=config.device_tier,
            ),
            event_sink=self._emit,
        )

        self._model = None
        if config.with_model:
            import jax
            import jax.numpy as jnp

            from llm_d_kv_cache_manager_tpu.models import llama

            mc = config.model_config or llama.LlamaConfig()
            self._model = llama
            self._model_config = mc
            self.params = params if params is not None else llama.init_params(
                mc, jax.random.PRNGKey(0)
            )
            if config.use_quantized_kv:
                self.kv_cache = llama.make_kv_pages_quantized(
                    mc, config.n_pages, config.page_size
                )
            else:
                self.kv_cache = llama.make_kv_pages(
                    mc, config.n_pages, config.page_size
                )
            self._jnp = jnp

    # -- events --------------------------------------------------------------

    def _emit(self, batch: EventBatch) -> None:
        if self._publisher is not None:
            self._publisher.publish(batch)
        if self._extra_sink is not None:
            self._extra_sink(batch)

    # -- serving -------------------------------------------------------------

    def prefill(
        self, tokens: List[int], lora_id: Optional[int] = None
    ) -> Tuple[SequenceState, int]:
        """Admit a sequence: allocate (with prefix reuse), compute the
        uncached suffix, commit pages + events. Returns (state, cached_tokens)."""
        state = self.block_manager.allocate(tokens, lora_id=lora_id)
        n_cached = state.num_cached_tokens
        if n_cached >= len(tokens):
            # Fully cached (modulo partial tail): recompute only the last
            # position for logits in model mode; no page writes needed.
            n_cached = min(n_cached, len(tokens) - 1)

        if self._model is not None:
            jnp = self._jnp
            block_table = self._padded_table(state)
            new_tokens = jnp.asarray(tokens[n_cached:], dtype=jnp.int32)
            self.kv_cache, self.last_logits = self._model.prefill_cache(
                self._model_config,
                self.params,
                self.kv_cache,
                new_tokens,
                block_table,
                n_cached,
            )

        self.block_manager.commit_prefill(state)
        return state, state.num_cached_tokens

    def decode_append(self, state: SequenceState, token: int) -> None:
        """Accounting-only decode: record one generated token."""
        self.block_manager.append_token(state, token)

    def decode_step(self, state: SequenceState) -> int:
        """Model decode: greedy-sample one token for this sequence."""
        if self._model is None:
            raise RuntimeError("decode_step requires with_model=True")
        jnp = self._jnp
        pos = len(state.tokens) - 1
        last_token = jnp.asarray([state.tokens[-1]], dtype=jnp.int32)
        # The last token's K/V were already written by prefill/previous step;
        # decode_step writes at seq_lens, so pass position of the new token.
        self.kv_cache, logits = self._model.decode_step_cache(
            self._model_config,
            self.params,
            self.kv_cache,
            last_token,
            self._padded_table(state)[None],
            jnp.asarray([pos], dtype=jnp.int32),
            self.config.use_kernel,
        )
        token = int(jnp.argmax(logits[0]))
        self.block_manager.append_token(state, token)
        return token

    def free(self, state: SequenceState) -> None:
        self.block_manager.free(state)

    def close(self) -> None:
        if self._publisher is not None:
            self._publisher.close()

    # -- helpers -------------------------------------------------------------

    def table_bucket(self, n_pages_needed: int) -> int:
        """Padded block-table width: next power of two covering the need, so
        short prompts don't pay attention compute over the maximal static
        shape; jit specializes per bucket. Single source of truth for both
        single-sequence and scheduler-batched decode shapes."""
        if n_pages_needed > self.config.max_pages_per_seq:
            raise ValueError(
                f"sequence needs {n_pages_needed} pages > "
                f"max_pages_per_seq={self.config.max_pages_per_seq}; truncating "
                "would silently corrupt K/V pages"
            )
        bucket = 1
        while bucket < max(n_pages_needed, 1):
            bucket *= 2
        return min(bucket, self.config.max_pages_per_seq)

    def _padded_table(self, state: SequenceState):
        bucket = self.table_bucket(len(state.block_table))
        jnp_or_np = self._jnp if self._model is not None else np
        table = np.zeros((bucket,), dtype=np.int32)
        table[: len(state.block_table)] = state.block_table
        return jnp_or_np.asarray(table)

"""Transfer-vs-recompute cost model for the two-tier data plane.

Whether moving a KV block beats recomputing it is pure arithmetic
intensity: restoring a block costs its `kv_bytes_per_token` over the
transfer path's bandwidth, while recomputing it costs the model's
`flops_per_token` over the chip's measured prefill rate. Wide / MQA /
int8-KV models carry few KV bytes per token of compute, so transfer wins;
small dense models recompute almost for free, so blind onboarding is a net
TTFT loss (round-3 measurement: 4x worse than recompute under
cache-oblivious routing, BENCH_r03.json two_tier rr_data_plane_speedup
0.252).

`TransferCostModel` makes the decision explicit. It is seeded from the
device-measured rates in benchmarking/DEVICE_BENCH.json (data-plane
bandwidths + marginal prefill TFLOP/s) whenever that artifact exists, so
the gate's economics are the rig's, not guesses; without the artifact it
falls back to labeled "assumed" v5e-class rates.

The reference has no equivalent surface: its kv_connectors/ directory is
an empty mandate (/root/reference/kv_connectors/, Makefile:169-175) and
its device tiers exist only as scoring weights
(/root/reference/pkg/kvcache/backend.go:19-31). This module is TPU-build
design: the data plane only fires when the bytes are cheaper than the
FLOPs.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, replace
from typing import Optional, Sequence

from llm_d_kv_cache_manager_tpu.utils import logging as kvlog

logger = kvlog.get_logger("engine.costs")

_DEVICE_BENCH_PATH = os.path.join(
    os.path.dirname(__file__), "..", "..", "benchmarking", "DEVICE_BENCH.json"
)

# Block sources a restorable chain prefix can mix, in the order load_chain
# resolves them: a payload the prefetcher already fetched into host RAM
# ("ready", pays only the device insert), the local host staging store
# ("staged", loopback fetch + insert), a peer pod over DCN ("peer",
# network fetch + insert).
READY, STAGED, PEER = "ready", "staged", "peer"

# Assumed v5e-class rates used only when no device measurement exists:
# host<->HBM over PCIe gen3 ~12 GB/s effective, DCN ~3 GB/s effective,
# marginal prefill ~80 TFLOP/s bf16. On the tunneled bench rig the
# measured rates are ~150x slower on the transfer side — which is exactly
# why the gate must be seeded from measurements, not these defaults.
ASSUMED_RATES = {
    "staged_bytes_per_s": 12e9,
    "peer_bytes_per_s": 3e9,
    "insert_bytes_per_s": 12e9,
    "compute_flops_per_s": 80e12,
    "source": "assumed (v5e-class; no DEVICE_BENCH.json)",
}


def measured_rates(path: Optional[str] = None) -> Optional[dict]:
    """Model-independent transfer/compute rates from the device bench
    artifact: bytes/s per path (derived from the benched model's measured
    s-per-token and its page geometry) and marginal prefill FLOP/s.
    Returns None when the artifact or its data_plane section is absent."""
    path = path or _DEVICE_BENCH_PATH
    try:
        with open(path) as f:
            bench = json.load(f)
    except (OSError, ValueError):
        return None
    dp = bench.get("data_plane") or {}
    if "page_nbytes" not in dp or "page_size_tokens" not in dp:
        return None
    bytes_per_token = dp["page_nbytes"] / dp["page_size_tokens"]

    def rate(key_batch: str, key_single: str) -> Optional[float]:
        s_per_token = dp.get(key_batch, dp.get(key_single))
        if not s_per_token:
            return None
        return bytes_per_token / s_per_token

    staged = rate("host_restore_batch_s_per_token", "host_restore_s_per_token")
    peer = rate("dcn_onboard_chain_s_per_token", "dcn_onboard_s_per_token")
    insert_mbps = dp.get("insert_batch_mbps", dp.get("insert_mbps"))
    tflops = (bench.get("analysis") or {}).get("prefill_marginal_tflops")
    if staged is None or tflops is None:
        return None
    source = "measured (DEVICE_BENCH.json)"
    if peer is None:
        # Artifact lacks the DCN leg (connector bench skipped): don't pass
        # a modeled number off under a measured label.
        source = "measured (DEVICE_BENCH.json; peer rate assumed staged/2)"
    return {
        "staged_bytes_per_s": staged,
        "peer_bytes_per_s": peer if peer is not None else staged / 2,
        "insert_bytes_per_s": (
            insert_mbps * 1e6 if insert_mbps else staged
        ),
        "compute_flops_per_s": tflops * 1e12,
        "source": source,
    }


def flops_per_token(model_config) -> float:
    """~2 FLOPs per parameter touched per token (matmul dominated):
    attention projections + gated MLP; embedding lookups free. The LM head
    is deliberately EXCLUDED: this function prices recomputing cached
    prefix KV blocks, and prefix tokens never produce logits (the head
    runs once per request, on the last position) — including it would
    overestimate recompute_s and bias the gate toward admitting transfers,
    the wrong direction for the no-regression guarantee."""
    c = model_config
    attn = (
        c.d_model * c.n_q_heads * c.head_dim  # wq
        + 2 * c.d_model * c.n_kv_heads * c.head_dim  # wk, wv
        + c.n_q_heads * c.head_dim * c.d_model  # wo
    )
    mlp = 3 * c.d_model * c.d_ff  # gate, up, down
    # The MoE family (models/mixtral.py) activates top_k experts per token.
    n_experts_active = getattr(c, "top_k", None)
    if getattr(c, "n_experts", 0) and n_experts_active:
        mlp = n_experts_active * mlp + c.d_model * c.n_experts  # + router
    return 2.0 * c.n_layers * (attn + mlp)


def kv_bytes_per_token(model_config, quantized: bool = False) -> float:
    """Bytes of KV cache one token occupies across all layers — the wire
    size of its share of a block payload (engine._DevicePageCodec layout:
    bf16 (k, v) pair, or int8 4-tuple with one f32 scale per row)."""
    c = model_config
    rows = 2 * c.n_layers * c.n_kv_heads  # k and v, every layer, every head
    if quantized:
        return rows * (c.head_dim * 1 + 4)  # int8 row + f32 scale
    return rows * c.head_dim * 2  # bf16


@dataclass(frozen=True)
class TransferCostModel:
    """Per-token seconds for THIS pod's model on THIS rig. `margin` < 1
    demands transfer beat recompute by that factor; > 1 tolerates slower
    transfers (e.g. to trade chip FLOPs for freshness under load)."""

    recompute_s: float
    staged_restore_s: float
    onboard_s: float
    insert_s: float
    margin: float = 1.0
    source: str = "assumed"

    def per_token(self, source: str) -> float:
        return {
            READY: self.insert_s,
            STAGED: self.staged_restore_s,
            PEER: self.onboard_s,
        }[source]

    def admit_prefix(self, sources: Sequence[str], page_size: int) -> int:
        """Longest chain prefix worth restoring. Restoring k blocks saves
        k * page_size tokens of recompute and costs the sum of their
        transfer times; admit the longest prefix whose cumulative cost
        stays within margin x savings (an expensive block can ride on the
        cheap ones behind it — chains restore as prefixes, never with
        holes)."""
        budget_per_block = self.margin * self.recompute_s * page_size
        cost = 0.0
        admitted = 0
        for i, source in enumerate(sources):
            cost += self.per_token(source) * page_size
            if cost <= budget_per_block * (i + 1):
                admitted = i + 1
        return admitted

    def with_margin(self, margin: float) -> "TransferCostModel":
        return replace(self, margin=margin)

    # -- constructors ------------------------------------------------------

    @classmethod
    def from_rates(
        cls,
        *,
        model_flops_per_token: float,
        model_kv_bytes_per_token: float,
        rates: Optional[dict] = None,
        margin: float = 1.0,
    ) -> "TransferCostModel":
        rates = rates or measured_rates() or ASSUMED_RATES
        return cls(
            recompute_s=model_flops_per_token / rates["compute_flops_per_s"],
            staged_restore_s=(
                model_kv_bytes_per_token / rates["staged_bytes_per_s"]
            ),
            onboard_s=model_kv_bytes_per_token / rates["peer_bytes_per_s"],
            insert_s=model_kv_bytes_per_token / rates["insert_bytes_per_s"],
            margin=margin,
            source=rates["source"],
        )

    @classmethod
    def for_model(
        cls,
        model_config,
        quantized: bool = False,
        rates: Optional[dict] = None,
        margin: float = 1.0,
    ) -> "TransferCostModel":
        """The default gate an EnginePod builds for its own model config:
        rig rates (measured when available) x this model's arithmetic
        intensity."""
        return cls.from_rates(
            model_flops_per_token=flops_per_token(model_config),
            model_kv_bytes_per_token=kv_bytes_per_token(
                model_config, quantized=quantized
            ),
            rates=rates,
            margin=margin,
        )


#: Gate that admits every restorable block — accounting-only pods (zero
#: payload bytes) and tests that pin restore mechanics rather than
#: economics.
ALWAYS_TRANSFER = TransferCostModel(
    recompute_s=1.0,
    staged_restore_s=0.0,
    onboard_s=0.0,
    insert_s=0.0,
    source="always-transfer",
)

#: Gate that refuses every transfer — the degenerate "recompute everything"
#: policy, useful as a bench arm.
NEVER_TRANSFER = TransferCostModel(
    recompute_s=0.0,
    staged_restore_s=1.0,
    onboard_s=1.0,
    insert_s=1.0,
    source="never-transfer",
)

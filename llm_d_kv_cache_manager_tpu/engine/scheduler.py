"""Continuous-batching scheduler for EnginePod.

The serving loop a vLLM-style engine runs: a waiting queue admits sequences
as pages free up through a **chunked prefill budget** — each tick computes
at most `prefill_token_budget` prompt tokens (a long prompt spans ticks,
several short prompts pack into one), so decode latency for the running
batch is bounded regardless of arrival sizes. All running sequences decode
together in one batched `decode_step_cache` call per tick. Per-sequence
block tables are padded to a shared bucket (EnginePod.table_bucket) so the
batch has one static shape per (batch-size, bucket) pair — a handful of jit
specializations, no dynamic shapes.

Capacity policy:
- `submit` rejects deterministically (empty result, `Request.error` set) any
  request whose prompt + max_new_tokens can never fit the pool or the
  per-sequence page cap — no stall heuristics.
- Decode-time page exhaustion preempts a sequence by recompute (vLLM-style):
  its pages are freed (staying prefix-cached), the request rejoins the
  waiting queue with its generated tokens folded into the prompt, and the
  re-prefill mostly hits the cache.

Token selection: greedy argmax by default; per-request SamplingParams
(temperature/top-k/top-p/seed) sample on device with per-position PRNG
keys, so output is reproducible and independent of decode_steps and batch
composition. Sequences finish on max_new_tokens or EOS.
"""

from __future__ import annotations

from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from llm_d_kv_cache_manager_tpu.engine.block_manager import (
    OutOfPagesError,
    SequenceState,
)
from llm_d_kv_cache_manager_tpu.engine.engine import EnginePod
from llm_d_kv_cache_manager_tpu.ops.sampling import (
    SamplingParams,
    position_keys,
    sample_tokens,
)


@dataclass
class Request:
    req_id: int
    prompt_tokens: List[int]
    max_new_tokens: int
    eos_token: Optional[int] = None
    lora_id: Optional[int] = None
    # None or greedy params => argmax. Sampled requests draw from
    # fold_in(PRNGKey(seed or req_id), position) per emitted position —
    # reproducible and identical across decode_steps settings
    # (ops/sampling.py).
    sampling: Optional["SamplingParams"] = None
    # Filled by the scheduler:
    state: Optional[SequenceState] = None
    generated: List[int] = field(default_factory=list)
    num_cached_tokens: int = 0
    # Chunked-prefill progress: next prompt position to compute, or None
    # when not mid-prefill.
    prefill_pos: Optional[int] = None
    finished: bool = False
    error: Optional[str] = None


class Scheduler:
    def __init__(
        self,
        pod: EnginePod,
        max_batch: int = 8,
        prefill_token_budget: int = 512,
        decode_steps: int = 1,
    ):
        if pod._model is None:
            raise ValueError("Scheduler requires an EnginePod with with_model=True")
        if prefill_token_budget < 1:
            raise ValueError("prefill_token_budget must be >= 1")
        if decode_steps < 1:
            raise ValueError("decode_steps must be >= 1")
        self.pod = pod
        self.max_batch = max_batch
        # decode_steps > 1: each decode tick runs ONE on-device multi-step
        # dispatch (models/llama.decode_multi_step_cache) emitting up to
        # decode_steps tokens per sequence — the dispatch-amortization lever
        # for the tunnel/host overhead that dominates per-step decode.
        # Output is identical to decode_steps=1 (greedy chain, same math,
        # pinned by tests); admission latency for waiting requests grows by
        # up to decode_steps-1 tokens per tick.
        self.decode_steps = decode_steps
        # vLLM-style chunked prefill: at most this many prompt tokens are
        # computed per tick, so a long-prompt arrival cannot stall the
        # running batch's decode for more than ~budget tokens of compute.
        self.prefill_token_budget = prefill_token_budget
        self._waiting: deque = deque()
        self._running: List[Request] = []
        self._rejected: List[Request] = []
        self._next_id = 0

    # -- API -----------------------------------------------------------------

    def submit(
        self,
        prompt_tokens: List[int],
        max_new_tokens: int = 16,
        eos_token: Optional[int] = None,
        lora_id: Optional[int] = None,
        sampling: Optional[SamplingParams] = None,
    ) -> int:
        req = Request(self._next_id, list(prompt_tokens), max_new_tokens,
                      eos_token, lora_id, sampling=sampling)
        self._next_id += 1

        error = self._validate(req)
        if error is not None:
            req.finished = True
            req.error = error
            self._rejected.append(req)
        else:
            self._waiting.append(req)
            # Data plane: start background payload fetches for restorable
            # blocks now, so the network leg rides the queue wait instead
            # of the admission tick (no-op without a host tier).
            self.pod.prefetch(req.prompt_tokens, req.lora_id)
        return req.req_id

    @property
    def has_work(self) -> bool:
        return bool(self._waiting or self._running or self._rejected)

    def step(self) -> List[Request]:
        """One scheduler tick: surface rejections, spend the prefill token
        budget (chunked, possibly across several waiting sequences), then
        one batched decode across running sequences. Returns newly finished
        requests (pages freed; cache stays warm)."""
        finished, self._rejected = self._rejected, []
        finished += self._prefill_tick()
        finished += self._decode()
        return finished

    def run(self) -> Dict[int, List[int]]:
        """Drain everything; returns {req_id: generated_tokens} (empty list
        for rejected requests — see Request.error on the returned objects of
        step() for the reason)."""
        results: Dict[int, List[int]] = {}
        while self.has_work:
            for req in self.step():
                results[req.req_id] = req.generated
        return results

    # -- internals -------------------------------------------------------------

    def _validate(self, req: Request) -> Optional[str]:
        if req.max_new_tokens < 1:
            return f"max_new_tokens must be >= 1, got {req.max_new_tokens}"
        try:
            self.pod.lora_index(req.lora_id)
        except KeyError as e:
            return f"unknown LoRA adapter: {e}"
        page_size = self.pod.config.page_size
        total_tokens = len(req.prompt_tokens) + req.max_new_tokens
        pages_needed = (total_tokens + page_size - 1) // page_size
        if pages_needed > self.pod.config.max_pages_per_seq:
            return (
                f"request needs {pages_needed} pages > max_pages_per_seq="
                f"{self.pod.config.max_pages_per_seq}"
            )
        if pages_needed > self.pod.config.n_pages:
            return (
                f"request needs {pages_needed} pages > pool size "
                f"{self.pod.config.n_pages}"
            )
        return None

    def _preempt(self, req: Request) -> None:
        """Recompute preemption: release pages (prefix stays cached), fold
        generated tokens into the prompt, rejoin the queue at the front —
        but never ahead of a mid-prefill request. That request holds its
        allocated pages and only makes progress at the queue head; queueing
        in front of it would deadlock the loop (it can't resume, its pages
        can't free, nothing else can allocate)."""
        self.pod.free(req.state)
        req.prompt_tokens = list(req.state.tokens)
        req.state = None
        req.prefill_pos = None
        if self._waiting and self._waiting[0].state is not None:
            self._waiting.insert(1, req)
        else:
            self._waiting.appendleft(req)

    def _prefill_tick(self) -> List[Request]:
        """Spend up to prefill_token_budget prompt tokens of compute. Long
        prompts span ticks (decode keeps running in between); short prompts
        pack — several can admit in one tick if the budget covers them.

        Packed prefill: the tick first PLANS every chunk it will compute
        (allocation + budget walk, no device work), then runs them all in
        ONE dispatch (EnginePod.prefill_chunk_batch — one weight stream for
        the whole admission wave), then resolves each completed prompt
        (commit, first-token sample from its logits row, admission)."""
        finished: List[Request] = []
        budget = self.prefill_token_budget

        # Plan: decide every (req, start, end) chunk this tick computes.
        jobs: List = []
        completed: List[Request] = []
        # First-page signatures of prompts with UNCOMMITTED compute in this
        # wave (newly admitted or resuming mid-prefill): a later arrival
        # sharing a full-block prefix with one of them must wait for the
        # NEXT wave — those pages commit only after this wave's dispatch,
        # and allocating it now would duplicate the pages and recompute the
        # shared prefix (any shared full-block prefix implies equal first
        # pages, so the signature cannot miss).
        ps = self.pod.config.page_size
        wave_first_pages = set()
        while (
            budget > 0 and self._waiting
            and len(self._running) + len(completed) < self.max_batch
        ):
            req = self._waiting[0]
            if req.state is None:
                if tuple(req.prompt_tokens[:ps]) in wave_first_pages:
                    break  # flush the wave; reuse its commits next tick
                try:
                    state, start = self.pod.begin_prefill(
                        req.prompt_tokens, lora_id=req.lora_id
                    )
                except OutOfPagesError:
                    break  # retry next tick once decodes free pages
                req.state = state
                req.num_cached_tokens = state.num_cached_tokens
                req.prefill_pos = start

            end = min(req.prefill_pos + budget, len(req.prompt_tokens))
            if end > req.prefill_pos:
                jobs.append((req, req.prefill_pos, end))
                wave_first_pages.add(tuple(req.prompt_tokens[:ps]))
                budget -= end - req.prefill_pos
                req.prefill_pos = end
            if req.prefill_pos < len(req.prompt_tokens):
                break  # budget exhausted mid-prompt; resume next tick
            completed.append(req)
            self._waiting.popleft()

        if not jobs:
            return finished

        # Dispatch: one batched device call for the whole wave.
        logits_rows = self.pod.prefill_chunk_batch(
            [(req.state, start, end) for req, start, end in jobs]
        )
        logits_by_req = {
            id(req): row for (req, _, _), row in zip(jobs, logits_rows)
        }

        # Resolve completed prompts: commit pages/events, sample the first
        # token from the final chunk's logits (for a re-admitted preempted
        # request this continues its generation). One argmax dispatch + one
        # host sync for the whole wave — per-prompt argmax would pay the
        # round-trip overhead the packed dispatch just amortized.
        jnp = self.pod._jnp
        first_tokens = {}
        if completed:
            stacked = jnp.stack([logits_by_req[id(r)] for r in completed])
            sarr = self._sampling_arrays(completed, len(completed))
            if sarr is None:
                toks = np.asarray(jnp.argmax(stacked, axis=-1))
            else:
                pos = jnp.asarray(
                    [len(r.state.tokens) - 1 for r in completed],
                    dtype=jnp.int32,
                )
                toks = np.asarray(sample_tokens(
                    stacked, sarr[0], sarr[1], sarr[2],
                    position_keys(sarr[3], pos),
                ))
            first_tokens = {id(r): int(t) for r, t in zip(completed, toks)}
        for req in completed:
            self.pod.finish_prefill(req.state)
            req.prefill_pos = None
            token = first_tokens[id(req)]
            req.generated.append(token)
            # A finished sequence never attends again — skip the (possibly
            # page-allocating) KV write for its final token.
            if self._done(req, token):
                req.finished = True
                self.pod.free(req.state)
                finished.append(req)
                continue
            try:
                self.pod.decode_append(req.state, token)
            except OutOfPagesError:
                self._preempt(req)  # token folds into the recompute prompt
                continue
            self._running.append(req)
        return finished

    @staticmethod
    def _done(req: Request, token: int) -> bool:
        return len(req.generated) >= req.max_new_tokens or (
            req.eos_token is not None and token == req.eos_token
        )

    def _assemble_batch(self, running: List[Request]):
        """Bucket-padded decode batch: (tables [Bp, bucket], pending tokens
        [Bp], positions [Bp]) — shared by the single-step and multi-step
        decode paths so they can never assemble inconsistently.

        Both axes are padded to power-of-2 buckets: the table width (page
        count) AND the batch size. Without batch bucketing every distinct
        running count compiles its own XLA program (seconds each on TPU) as
        sequences finish. Pad rows carry seq_len 0 and an all-trash-page
        block table, so their (discarded) step still writes only the
        sacrificial page — they can never corrupt real pages; callers index
        outputs by the real running list, which drops pad rows naturally."""
        need = max(len(r.state.block_table) for r in running)
        bucket = self.pod.table_bucket(need)
        b_pad = self.pod.batch_bucket(len(running))
        tables = np.full((b_pad, bucket), self.pod.trash_page, dtype=np.int32)
        tokens = np.zeros((b_pad,), dtype=np.int32)
        positions = np.zeros((b_pad,), dtype=np.int32)
        for i, req in enumerate(running):
            bt = req.state.block_table
            tables[i, : len(bt)] = bt
            tokens[i] = req.state.tokens[-1]
            positions[i] = len(req.state.tokens) - 1
        return tables, tokens, positions

    def _sampling_arrays(self, reqs: List[Request], padded_len: int):
        """None when every request is greedy (the common case keeps its
        argmax trace); otherwise (temps, top_ks, top_ps, base_keys) padded
        to `padded_len` with greedy pad rows. Base keys come from the
        request seed (default: req_id), so a run is reproducible and a
        request's draws don't depend on what it was batched with.

        Cached per (request-set, padded_len), a few entries deep: prefill
        finishes (padded to the wave length) and decode ticks (padded to
        the batch bucket) alternate with different signatures, so a
        single-slot cache would rebuild + re-upload the arrays every tick
        — exactly the cost the cache exists to avoid."""
        if all(r.sampling is None or r.sampling.is_greedy for r in reqs):
            return None
        sig = (tuple((r.req_id, r.sampling) for r in reqs), padded_len)
        cache = getattr(self, "_sampling_cache", None)
        if cache is None:
            cache = self._sampling_cache = OrderedDict()
        cached = cache.get(sig)
        if cached is not None:
            cache.move_to_end(sig)
            return cached
        import jax

        jnp = self.pod._jnp
        temps = np.zeros((padded_len,), np.float32)
        top_ks = np.zeros((padded_len,), np.int32)
        top_ps = np.ones((padded_len,), np.float32)
        keys = [jax.random.PRNGKey(0)] * padded_len
        for i, r in enumerate(reqs):
            sp = r.sampling
            if sp is not None and not sp.is_greedy:
                temps[i] = sp.temperature
                top_ks[i] = sp.top_k
                top_ps[i] = sp.top_p
                keys[i] = jax.random.PRNGKey(
                    sp.seed if sp.seed is not None else r.req_id
                )
        arrays = (
            jnp.asarray(temps), jnp.asarray(top_ks), jnp.asarray(top_ps),
            jnp.stack(keys),
        )
        cache[sig] = arrays
        while len(cache) > 8:  # a handful of live shapes; bound the rest
            cache.popitem(last=False)
        return arrays

    def _decode(self) -> List[Request]:
        if not self._running:
            return []
        if self.decode_steps > 1:
            return self._decode_multi()
        jnp = self.pod._jnp
        tables, tokens, positions = self._assemble_batch(self._running)
        # Pad-row adapters are base (index 0) — their output is discarded.
        lora_ids = [r.lora_id for r in self._running]
        lora_ids += [None] * (len(tokens) - len(lora_ids))

        self.pod.kv_cache, logits = self.pod._model.decode_step_cache(
            self.pod._model_config,
            self.pod.params,
            self.pod.kv_cache,
            jnp.asarray(tokens),
            jnp.asarray(tables),
            jnp.asarray(positions),
            self.pod.config.use_kernel,
            lora=self.pod.lora_for_decode(lora_ids),
        )
        sarr = self._sampling_arrays(self._running, len(tokens))
        if sarr is None:
            next_tokens = np.asarray(jnp.argmax(logits, axis=-1))
        else:
            next_tokens = np.asarray(sample_tokens(
                logits, sarr[0], sarr[1], sarr[2],
                position_keys(sarr[3], jnp.asarray(positions)),
            ))

        # Every running sequence's pending token just had its KV row
        # written: commit pages that row completed (this is the only point
        # a decode-filled page becomes advertisable — append_token defers).
        for req in self._running:
            self.pod.block_manager.mark_decode_computed(req.state)

        finished: List[Request] = []
        still_running: List[Request] = []
        for req, token in zip(self._running, next_tokens):
            token = int(token)
            req.generated.append(token)
            if self._done(req, token):
                req.finished = True
                self.pod.free(req.state)
                finished.append(req)
                continue
            try:
                self.pod.decode_append(req.state, token)
            except OutOfPagesError:
                self._preempt(req)  # tokens incl. this one fold into prompt
                continue
            still_running.append(req)
        self._running = still_running
        return finished

    def _decode_multi(self) -> List[Request]:
        """One decode tick emitting up to `decode_steps` tokens per sequence
        from a single on-device dispatch (lax.scan over the step body with
        on-device argmax — models/llama.decode_multi_step_cache).

        Per-sequence accept counts: sequence i accepts k_i = min(N,
        remaining budget, page capacity) tokens; the device still runs all
        N steps for the rectangular batch, steering row writes past
        position seq_len + k_i into the pod's trash page. Host-side append
        then replays the accepted tokens exactly like N plain ticks — the
        final accepted token becomes the new pending token.
        """
        pod = self.pod
        jnp = pod._jnp
        n = self.decode_steps
        ps = pod.config.page_size
        running = self._running

        # Reserve write headroom per sequence: accepting k tokens writes
        # rows at positions len-1 .. len+k-2, i.e. len+k-1 positions total.
        # On pool exhaustion degrade to k=1 (the pending token's page is
        # already held, so a single step never needs new reservations).
        accepts: List[int] = []
        for req in running:
            length = len(req.state.tokens)
            capacity = pod.config.max_pages_per_seq * ps - length + 1
            k = max(1, min(n, req.max_new_tokens - len(req.generated), capacity))
            try:
                pod.block_manager.reserve_pages(
                    req.state, (length + k - 1 + ps - 1) // ps
                )
            except OutOfPagesError:
                k = 1
            accepts.append(k)

        tables, tokens, positions = self._assemble_batch(running)
        # Pad rows: 0 rows allowed (every write lands in the trash page)
        # and base adapter; their sampled tokens are never read.
        padded_accepts = accepts + [0] * (len(tokens) - len(accepts))
        max_lens = positions + np.asarray(padded_accepts, dtype=np.int32)
        lora_ids = [r.lora_id for r in running]
        lora_ids += [None] * (len(tokens) - len(lora_ids))

        pod.kv_cache, toks = pod._model.decode_multi_step_cache(
            pod._model_config,
            pod.params,
            pod.kv_cache,
            jnp.asarray(tokens),
            jnp.asarray(tables),
            jnp.asarray(positions),
            jnp.asarray(max_lens),
            pod.trash_page,
            n,
            pod.config.use_kernel,
            lora=pod.lora_for_decode(lora_ids),
            sampling=self._sampling_arrays(running, len(tokens)),
        )
        toks = np.asarray(toks)  # [B, n]

        finished: List[Request] = []
        still_running: List[Request] = []
        for i, req in enumerate(running):
            # The pending token's row was written by step 0 (it is always
            # within max_lens): pages it completed become advertisable.
            pod.block_manager.mark_decode_computed(req.state)
            done = False
            preempted = False
            k = accepts[i]
            for j in range(k):
                token = int(toks[i, j])
                req.generated.append(token)
                if self._done(req, token):
                    done = True
                    break
                try:
                    self.pod.decode_append(req.state, token)
                except OutOfPagesError:
                    self._preempt(req)
                    preempted = True
                    break
                # All accepted tokens except the last have device-resident
                # KV (each was consumed by a later in-window step); the
                # last accepted token is the new pending.
                if j < k - 1:
                    pod.block_manager.mark_decode_computed(req.state)
            if done:
                req.finished = True
                # Every token still in the sequence has resident KV (the
                # done token is never appended) — commit the tail page so
                # it stays reusable.
                pod.block_manager.mark_decode_computed(req.state)
                self.pod.free(req.state)
                finished.append(req)
                continue
            if preempted:
                continue
            still_running.append(req)
        self._running = still_running
        return finished

"""Chat-completions preprocessing: Jinja chat-template rendering + fetching.

Parity target: the reference's preprocessing layer
(/root/reference/pkg/preprocessing/chat_completions/): a Go↔C↔embedded-CPython
bridge (cgo_functions.c:40-86,148-225) that calls
`transformers.utils.chat_template_utils.render_jinja_template` and fetches
model chat templates, with module-level template caching
(render_jinja_template_wrapper.py:81-207).

This build is Python-native, so the entire FFI tower collapses into a direct
call into `transformers` — same JSON contract, no GIL gymnastics. The
templating seam is kept as a class so the UDS sidecar can serve it
out-of-process when the control plane itself is run natively (C++ service
embedding CPython, services/uds_tokenizer/).
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from llm_d_kv_cache_manager_tpu.utils import logging as kvlog

logger = kvlog.get_logger("preprocessing.chat_completions")


@dataclass
class RenderRequest:
    """Mirror of the reference's RenderJinjaTemplateRequest JSON contract."""

    conversations: List[List[Dict[str, Any]]]
    chat_template: Optional[str] = None
    tools: Optional[List[Dict[str, Any]]] = None
    documents: Optional[List[Dict[str, Any]]] = None
    add_generation_prompt: bool = True
    continue_final_message: bool = False
    template_vars: Dict[str, Any] = field(default_factory=dict)
    model_name: Optional[str] = None

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "RenderRequest":
        """The one place the JSON body contract maps to fields — shared by
        the HTTP service, the UDS sidecar, and from_json."""
        if "conversations" in data:
            conversations = data["conversations"]
        else:
            conversations = [data["messages"]]
        return cls(
            conversations=conversations,
            chat_template=data.get("chat_template"),
            tools=data.get("tools"),
            documents=data.get("documents"),
            add_generation_prompt=data.get("add_generation_prompt", True),
            continue_final_message=data.get("continue_final_message", False),
            template_vars=data.get("template_vars", {}),
            model_name=data.get("model"),
        )

    @classmethod
    def from_json(cls, payload: str) -> "RenderRequest":
        return cls.from_dict(json.loads(payload))


class ChatTemplatingProcessor:
    """Renders chat templates and fetches/caches per-model templates."""

    def __init__(self):
        self._template_cache: Dict[str, str] = {}
        self._mu = threading.Lock()

    def render(self, request: RenderRequest) -> str:
        """Render the first conversation to a prompt string."""
        template = request.chat_template
        if not template and request.model_name:
            template = self.fetch_chat_template(request.model_name)
        if not template:
            raise ValueError("no chat template provided or fetchable")

        from transformers.utils.chat_template_utils import render_jinja_template

        rendered, _generation_indices = render_jinja_template(
            conversations=request.conversations,
            chat_template=template,
            tools=request.tools,
            documents=request.documents,
            add_generation_prompt=request.add_generation_prompt,
            continue_final_message=request.continue_final_message,
            **request.template_vars,
        )
        return rendered[0]

    def fetch_chat_template(
        self, model_name: str, local_dir: Optional[str] = None
    ) -> Optional[str]:
        """Fetch a model's chat template, caching per model.

        Resolution order: cache → local `tokenizer_config.json` /
        `chat_template.jinja` (under `local_dir` or LOCAL_TOKENIZER_DIR) →
        `transformers.AutoTokenizer` (may hit the network).
        """
        with self._mu:
            cached = self._template_cache.get(model_name)
        if cached is not None:
            return cached

        template = self._fetch_local(model_name, local_dir)
        if template is None:
            template = self._fetch_auto(model_name)
        if template is not None:
            with self._mu:
                self._template_cache[model_name] = template
        return template

    def clear_caches(self) -> None:
        with self._mu:
            self._template_cache.clear()

    def _fetch_local(self, model_name: str, local_dir: Optional[str]) -> Optional[str]:
        root = local_dir or os.environ.get("LOCAL_TOKENIZER_DIR", "")
        if not root:
            return None
        candidates = [
            os.path.join(root, model_name),
            os.path.join(root, model_name.replace("/", os.sep)),
        ]
        for base in candidates:
            jinja_path = os.path.join(base, "chat_template.jinja")
            if os.path.isfile(jinja_path):
                with open(jinja_path, encoding="utf-8") as f:
                    return f.read()
            cfg_path = os.path.join(base, "tokenizer_config.json")
            if os.path.isfile(cfg_path):
                try:
                    with open(cfg_path, encoding="utf-8") as f:
                        cfg = json.load(f)
                    template = cfg.get("chat_template")
                    if isinstance(template, str):
                        return template
                except (OSError, json.JSONDecodeError) as e:
                    logger.warning("failed reading %s: %s", cfg_path, e)
        return None

    def _fetch_auto(self, model_name: str) -> Optional[str]:
        try:
            from transformers import AutoTokenizer

            tok = AutoTokenizer.from_pretrained(model_name)
            template = getattr(tok, "chat_template", None)
            return template if isinstance(template, str) else None
        except Exception as e:  # noqa: BLE001 - network/model errors are soft
            logger.warning("AutoTokenizer template fetch failed for %s: %s", model_name, e)
            return None

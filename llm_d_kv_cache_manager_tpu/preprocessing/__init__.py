from llm_d_kv_cache_manager_tpu.preprocessing.chat_completions import (
    ChatTemplatingProcessor,
    RenderRequest,
)

__all__ = ["ChatTemplatingProcessor", "RenderRequest"]

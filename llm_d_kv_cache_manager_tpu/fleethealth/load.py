"""Per-pod load signals for the saturation-resilient routing policy.

The prefix index answers "who has my cache"; nothing in the read path
answers "who can actually take my request". Under saturation that gap is
the whole failure mode (ROADMAP item 4, FLEET_BENCH.json `qps_ladder`
qps_40): the router keeps maximizing prefix hit rate while the winning
pod's admission queue deepens and its page pool churns through
recompute-preemptions — a perfect-prefix pod that is 10 requests deep
loses to recompute on an idle pod, but pure prefix scoring cannot see it.

`PodLoadTracker` is the read side's load oracle. Signals, per pod:

- **queue_depth / inflight** — reported by a lightweight pod-load reporter
  (the serving sim reports its own bookkeeping; a real deployment scrapes
  the engine's admission queue or has pods POST it). Reports carry the
  reporter's notion of pending work; the tracker only stores and ages
  them.
- **busy_s** — how far into the future the pod's prefill slot is already
  committed (the router-side queue-wait estimate).
- **preemption rate** — exponentially-decayed count of
  recompute-preemptions, fed either by explicit `observe_preemption`
  calls or from the kvevents stream (the event pool credits BlockRemoved
  bursts via `observe_removed_blocks`; eviction volume is the wire-visible
  trace of page-pool churn).

Reports age out (`stale_report_after_s`): a pod that stopped reporting
contributes no load signal rather than an eternally-frozen one — absent
evidence must not repel traffic forever. Like the health tracker, state
evaluation is lazy and clock-driven: no threads, injectable clock, fully
deterministic under the simulated-clock benches.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import base_pod_identifier


@dataclass
class PodLoadConfig:
    # Half-life of the decayed preemption/eviction-pressure counters: with
    # the 30s default, "preemption_rate 4.0" reads as "~4 recent
    # preemptions' worth of churn", not a lifetime count.
    preemption_half_life_s: float = 30.0
    # Queue/inflight reports older than this contribute nothing (the
    # reporter died or the pod left; frozen load must not keep repelling
    # or attracting traffic).
    stale_report_after_s: float = 10.0
    # Removed-block volume is a noisy proxy for preemption churn: one
    # preemption reclaims a whole sequence's pages. This many removed
    # blocks count as one preemption-equivalent in the pressure signal.
    removed_blocks_per_preemption: float = 64.0


@dataclass
class PodLoad:
    """One pod's current load snapshot (already aged by the tracker)."""

    queue_depth: float = 0.0
    inflight: float = 0.0
    busy_s: float = 0.0
    preemption_rate: float = 0.0

    def as_dict(self) -> dict:
        return {
            "queue_depth": round(self.queue_depth, 3),
            "inflight": round(self.inflight, 3),
            "busy_s": round(self.busy_s, 4),
            "preemption_rate": round(self.preemption_rate, 3),
        }


class _LoadRecord:
    __slots__ = (
        "queue_depth", "inflight", "busy_at", "busy_reported_t",
        "reported_t", "preempt_value", "preempt_t",
    )

    def __init__(self):
        self.queue_depth = 0.0
        self.inflight = 0.0
        # busy_at is an absolute "free at" clock value; busy_s at read time
        # is max(0, busy_at - now), so the estimate drains by itself.
        self.busy_at = 0.0
        self.busy_reported_t: Optional[float] = None
        self.reported_t: Optional[float] = None
        self.preempt_value = 0.0
        self.preempt_t: Optional[float] = None


class PodLoadTracker:
    """Aged per-pod load signals keyed by BASE pod identity (DP-rank
    suffixes stripped — load is a per-pod property; every rank of a pod
    shares one admission queue in the deployments this models)."""

    def __init__(
        self,
        config: Optional[PodLoadConfig] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.config = config or PodLoadConfig()
        if self.config.preemption_half_life_s <= 0:
            raise ValueError("preemption_half_life_s must be positive")
        self.clock = clock
        self._mu = threading.Lock()
        self._pods: Dict[str, _LoadRecord] = {}
        self._lambda = math.log(2.0) / self.config.preemption_half_life_s

    # -- reporter seam -----------------------------------------------------

    def report(
        self,
        pod_identifier: str,
        queue_depth: float = 0.0,
        inflight: float = 0.0,
        busy_until: Optional[float] = None,
        now: Optional[float] = None,
    ) -> None:
        """One pod-load report. `busy_until` is an absolute clock value
        ("this pod's prefill slot frees at t"); queue_depth/inflight are
        instantaneous gauges that age out after `stale_report_after_s`."""
        if now is None:
            now = self.clock()
        pod = base_pod_identifier(pod_identifier)
        with self._mu:
            rec = self._pods.get(pod)
            if rec is None:
                rec = self._pods[pod] = _LoadRecord()
            rec.queue_depth = float(queue_depth)
            rec.inflight = float(inflight)
            rec.reported_t = now
            if busy_until is not None:
                rec.busy_at = float(busy_until)
                rec.busy_reported_t = now

    def observe_preemption(
        self, pod_identifier: str, n: float = 1.0, now: Optional[float] = None
    ) -> None:
        """Credit `n` recompute-preemptions to the pod's decayed rate."""
        if n <= 0:
            return
        if now is None:
            now = self.clock()
        pod = base_pod_identifier(pod_identifier)
        with self._mu:
            rec = self._pods.get(pod)
            if rec is None:
                rec = self._pods[pod] = _LoadRecord()
            rec.preempt_value = self._decayed(rec, now) + float(n)
            rec.preempt_t = now

    def observe_removed_blocks(
        self, pod_identifier: str, n_blocks: int, now: Optional[float] = None
    ) -> None:
        """kvevents feed: BlockRemoved volume as preemption-equivalent
        pressure (the event pool calls this per digested removal event)."""
        per = max(self.config.removed_blocks_per_preemption, 1e-9)
        self.observe_preemption(pod_identifier, n_blocks / per, now=now)

    # -- read side ---------------------------------------------------------

    def _decayed(self, rec: _LoadRecord, now: float) -> float:
        if rec.preempt_t is None or rec.preempt_value <= 0.0:
            return 0.0
        dt = max(0.0, now - rec.preempt_t)
        return rec.preempt_value * math.exp(-self._lambda * dt)

    def load_of(
        self, pod_identifier: str, now: Optional[float] = None
    ) -> PodLoad:
        """Current aged snapshot; unknown pods read as idle (no evidence
        is no load — the policy must not punish a pod for silence)."""
        if now is None:
            now = self.clock()
        pod = base_pod_identifier(pod_identifier)
        with self._mu:
            rec = self._pods.get(pod)
            if rec is None:
                return PodLoad()
            out = PodLoad(preemption_rate=self._decayed(rec, now))
            fresh_for = self.config.stale_report_after_s
            if (
                rec.reported_t is not None
                and now - rec.reported_t < fresh_for
            ):
                out.queue_depth = rec.queue_depth
                out.inflight = rec.inflight
            if (
                rec.busy_reported_t is not None
                and now - rec.busy_reported_t < fresh_for
            ):
                out.busy_s = max(0.0, rec.busy_at - now)
            return out

    def forget_pod(self, pod_identifier: str) -> int:
        """Drop a departed pod's load record (the resourcegov reap hook;
        DP-rank-qualified identities fold onto their base key). A
        returning pod re-learns from its first report. Returns rows
        removed (0 or 1 — load is one record per base identity)."""
        pod = base_pod_identifier(pod_identifier)
        with self._mu:
            return 1 if self._pods.pop(pod, None) is not None else 0

    def entries(self) -> int:
        """Tracked per-pod load rows — the resource accountant's O(1)
        meter read."""
        with self._mu:
            return len(self._pods)

    def snapshot(self, now: Optional[float] = None) -> Dict[str, dict]:
        """{pod: load dict} for /readyz-style introspection."""
        if now is None:
            now = self.clock()
        with self._mu:
            pods = sorted(self._pods)
        return {pod: self.load_of(pod, now=now).as_dict() for pod in pods}

"""Pod-lifecycle & staleness tracking for the KV-block control plane.

The index is a *near-real-time* view of the fleet's cache placement, kept
fresh only by the engines' event streams — the reference has no notion of
that view going bad (SURVEY §5): a crashed pod, a stalled ZMQ stream, or
dropped event batches leave phantom placements that `GetPodScores` keeps
routing traffic to. Mooncake-style cache-aware routing makes the same
observation from the engine side: placement metadata is only worth
following while it is trustworthy, and the router must degrade to plain
load-based decisions when it is not.

This tracker makes staleness a first-class state:

- **Liveness.** Every decoded `EventBatch` stamps its (DP-rank-qualified)
  pod identity with the tracker clock. A pod whose stream goes quiet
  transitions ``healthy → suspect → stale`` on configurable windows;
  events resuming at any point transition it straight back to healthy
  (a "recovery", counted).
- **Stream-integrity detection.** Per (pod, topic): the wire `seq` must
  advance by exactly 1 — a larger jump is a *gap* (dropped batches), a
  smaller/equal value is a *reorder*/*duplicate*; the batch `ts` must be
  non-decreasing within tolerance (*ts_regression*). Anomalies are
  counted per pod and fleet-wide (``kvcache_event_stream_anomalies_total``)
  — they are evidence the index may have silently diverged even while the
  pod looks live.
- **Quarantine.** On the stale transition (and on explicit
  `quarantine()`), the pod's entries are purged from the shared index in
  one bulk `Index.remove_pod` pass — phantom blocks stop scoring the
  moment staleness is *detected*, instead of leaking until LRU churn.
  Detection latency (stale-detected minus last-event) is recorded per pod
  and is bounded by ``stale_after_s`` plus the caller's evaluation cadence.
- **Graceful degradation.** `filter_scores` is the read-path hook
  (`kvcache/indexer.py`): healthy pods pass through untouched (bit-
  identical scores on a healthy fleet — pinned by the no-fault bench
  runs), suspect pods are demoted by ``suspect_demotion_factor``, and
  stale pods are excluded entirely. A score map that empties out is the
  explicit "no cache signal" answer — the router falls back to its
  load/round-robin strategy rather than chasing phantom placements.

State evaluation is *lazy and clock-driven*: there is no background
thread. `refresh()` runs on every `filter_scores` call (O(pods)) and can
be called explicitly; the clock is injectable, so every transition is
deterministic under test and under the fault-injection bench
(`bench.py --faults`).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import (
    base_pod_identifier,
)
from llm_d_kv_cache_manager_tpu.metrics import collector as metrics
from llm_d_kv_cache_manager_tpu.utils import logging as kvlog

logger = kvlog.get_logger("fleethealth.tracker")

HEALTHY = "healthy"
SUSPECT = "suspect"
STALE = "stale"


@dataclass
class FleetHealthConfig:
    # Quiet-stream windows: a pod with no decoded events for
    # `suspect_after_s` is demoted; for `stale_after_s` it is excluded and
    # its index entries purged. Production defaults are deliberately
    # generous — event silence also happens on genuinely idle pods, and a
    # false quarantine costs cache hits (never correctness: entries
    # repopulate from the live stream on the next store).
    suspect_after_s: float = 30.0
    stale_after_s: float = 120.0
    # Multiplier applied to a suspect pod's score (1.0 = no demotion).
    suspect_demotion_factor: float = 0.5
    # Purge a pod's index entries automatically on the stale transition.
    auto_quarantine: bool = True
    # Batch `ts` may regress by up to this much (clock skew between a
    # pod's DP ranks publishing on one topic) before counting an anomaly.
    ts_regression_tolerance_s: float = 1.0


class _PodRecord:
    __slots__ = (
        "last_event_t", "state", "state_since", "last_seq", "last_ts",
        "seq_gaps", "gap_events", "duplicates", "reorders",
        "ts_regressions", "decode_failures", "recoveries",
        "stale_detected_at", "detection_latency_s", "purged_entries",
    )

    def __init__(self, now: float):
        self.last_event_t = now
        self.state = HEALTHY
        self.state_since = now
        self.last_seq: Dict[str, int] = {}
        self.last_ts: Optional[float] = None
        self.seq_gaps = 0
        self.gap_events = 0  # estimated batches lost inside the gaps
        self.duplicates = 0
        self.reorders = 0
        self.ts_regressions = 0
        self.decode_failures = 0
        self.recoveries = 0
        self.stale_detected_at: Optional[float] = None
        self.detection_latency_s: Optional[float] = None
        self.purged_entries = 0

    def as_dict(self) -> dict:
        return {
            "state": self.state,
            "last_event_age_s": None,  # filled by summary() with the clock
            "seq_gaps": self.seq_gaps,
            "gap_events": self.gap_events,
            "duplicates": self.duplicates,
            "reorders": self.reorders,
            "ts_regressions": self.ts_regressions,
            "decode_failures": self.decode_failures,
            "recoveries": self.recoveries,
            "detection_latency_s": self.detection_latency_s,
            "purged_entries": self.purged_entries,
        }


class FleetHealthTracker:
    """Per-(pod, dp_rank) liveness + stream integrity + degraded scoring.

    Pods are keyed by the same DP-rank-qualified identity the event pool
    writes into the index ("pod@dpR" for DP>1 engines, the bare pod name
    otherwise), so health state and score keys always line up.
    """

    def __init__(
        self,
        config: Optional[FleetHealthConfig] = None,
        index=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.config = config or FleetHealthConfig()
        if self.config.stale_after_s < self.config.suspect_after_s:
            raise ValueError(
                "stale_after_s must be >= suspect_after_s "
                f"({self.config.stale_after_s} < {self.config.suspect_after_s})"
            )
        self.index = index
        self.clock = clock
        self._mu = threading.Lock()
        self._pods: Dict[str, _PodRecord] = {}
        # Subscriber stream state (surfaced by /readyz).
        self._subscriber_failures = 0
        self._subscriber_connected: Optional[bool] = None
        # Data-plane peer breaker states (kv_connectors TransferClient
        # transitions), keyed by peer "host:port". A peer whose transfer
        # breaker is open is a different signal from a stale event stream
        # — the pod may still be scoring fresh placements while its
        # transfer NIC is dark — so it is reported alongside, not merged
        # into, the pod liveness state machine.
        self._transfer_peers: Dict[str, dict] = {}
        # Departure seam: fired (outside the lock) after a stale
        # quarantine finishes purging, with the pod identity — the
        # resourcegov DepartureReaper attaches here so a pod that is gone
        # in practice is reaped like one that left on purpose.
        self.on_departed: Optional[Callable[[str], None]] = None

    def bind_index(self, index) -> None:
        """Late-bind the index quarantine target (Indexer wiring order)."""
        self.index = index

    # -- write-plane observations -----------------------------------------

    def observe_batch(
        self, pod_identifier: str, topic: str, seq: Optional[int], ts: float
    ) -> None:
        """Stamp liveness + check stream integrity for one decoded batch.

        Called by the event-pool worker after decode, with the DP-rank-
        qualified pod identity. `seq` is the wire frame's per-publisher
        monotonic sequence (None when the transport carries none).
        """
        now = self.clock()
        with self._mu:
            rec = self._pods.get(pod_identifier)
            if rec is None:
                rec = _PodRecord(now)
                self._pods[pod_identifier] = rec
            rec.last_event_t = now
            if rec.state != HEALTHY:
                # Events resumed: the stream is discontinuous by
                # definition (restart/stall), so reset seq tracking
                # instead of flagging the fresh stream as one giant gap.
                self._transition(rec, pod_identifier, HEALTHY, now)
                rec.recoveries += 1
                rec.last_seq.clear()
                rec.last_ts = None
                rec.stale_detected_at = None
            if seq is not None:
                last = rec.last_seq.get(topic)
                if last is not None:
                    if seq == last:
                        rec.duplicates += 1
                        metrics.count_stream_anomaly("duplicate")
                    elif seq < last:
                        rec.reorders += 1
                        metrics.count_stream_anomaly("reorder")
                    elif seq > last + 1:
                        rec.seq_gaps += 1
                        rec.gap_events += seq - last - 1
                        metrics.count_stream_anomaly("seq_gap")
                        logger.warning(
                            "event seq gap on %s topic=%s: %d -> %d "
                            "(%d batch(es) lost)",
                            pod_identifier, topic, last, seq, seq - last - 1,
                        )
                rec.last_seq[topic] = max(last or 0, seq)
            if rec.last_ts is not None and (
                ts + self.config.ts_regression_tolerance_s < rec.last_ts
            ):
                rec.ts_regressions += 1
                metrics.count_stream_anomaly("ts_regression")
            rec.last_ts = max(rec.last_ts or ts, ts)

    def observe_decode_failure(self, pod_identifier: str) -> None:
        """A poison-pill frame: the stream is alive but carrying garbage."""
        now = self.clock()
        with self._mu:
            rec = self._pods.get(pod_identifier)
            if rec is None:
                rec = _PodRecord(now)
                self._pods[pod_identifier] = rec
            rec.decode_failures += 1
            # Liveness is NOT stamped: a pod emitting only undecodable
            # frames provides no evidence its placement data is fresh.

    # -- subscriber stream state (zmq_subscriber.py) -----------------------

    def observe_subscriber_failure(self, consecutive: int) -> None:
        with self._mu:
            self._subscriber_failures = consecutive
            self._subscriber_connected = False

    def observe_subscriber_connected(self) -> None:
        with self._mu:
            self._subscriber_failures = 0
            self._subscriber_connected = True

    # -- data-plane breaker feed (kv_connectors/connector.py) --------------

    def observe_transfer_breaker(
        self, peer: str, old_state: str, new_state: str
    ) -> None:
        """One per-peer transfer-breaker transition (the TransferClient's
        `on_breaker_transition` callback lands here). Kept as a bounded
        per-peer record for /readyz and the fault bench — peers are fleet
        topology, never traffic."""
        now = self.clock()
        with self._mu:
            rec = self._transfer_peers.get(peer)
            if rec is None:
                rec = self._transfer_peers[peer] = {
                    "state": new_state, "since": now, "transitions": 0,
                    "opens": 0,
                }
            rec["state"] = new_state
            rec["since"] = now
            rec["transitions"] += 1
            if new_state == "open":
                rec["opens"] += 1
        log = logger.info if new_state == "closed" else logger.warning
        log("transfer breaker for peer %s: %s -> %s", peer, old_state,
            new_state)

    def transfer_breaker_summary(self) -> dict:
        with self._mu:
            return {
                peer: dict(rec)
                for peer, rec in sorted(self._transfer_peers.items())
            }

    # -- state machine -----------------------------------------------------

    def _expected_state(self, rec: _PodRecord, now: float) -> str:
        age = now - rec.last_event_t
        if age >= self.config.stale_after_s:
            return STALE
        if age >= self.config.suspect_after_s:
            return SUSPECT
        return HEALTHY

    def _transition(
        self, rec: _PodRecord, pod: str, new_state: str, now: float
    ) -> None:
        """Record a state change. Caller holds `_mu`."""
        old = rec.state
        rec.state = new_state
        rec.state_since = now
        metrics.count_pod_transition(new_state)
        log = logger.info if new_state == HEALTHY else logger.warning
        log("pod %s: %s -> %s (last event %.1fs ago)",
            pod, old, new_state, now - rec.last_event_t)

    def refresh(self, now: Optional[float] = None) -> None:
        """Advance every pod's state to what the clock says it should be.

        Quarantine (the index purge) runs OUTSIDE the tracker lock — the
        index has its own locking, and a slow/remote backend must not
        block concurrent observe_batch calls.
        """
        if now is None:
            now = self.clock()
        to_purge: List[str] = []
        with self._mu:
            for pod, rec in self._pods.items():
                expected = self._expected_state(rec, now)
                if expected == rec.state:
                    continue
                self._transition(rec, pod, expected, now)
                if expected == STALE:
                    rec.stale_detected_at = now
                    rec.detection_latency_s = now - rec.last_event_t
                    if self.config.auto_quarantine:
                        to_purge.append(pod)
        for pod in to_purge:
            self._purge(pod)

    def _purge(self, pod: str) -> None:
        if self.index is None:
            self._fire_departed(pod)
            return
        try:
            removed = self.index.remove_pod(pod)
        except Exception as e:  # noqa: BLE001 - a purge failure must not
            # unwind the read path; the pod stays excluded by state anyway.
            logger.warning("failed to purge stale pod %s from index: %s", pod, e)
            return
        metrics.count_stale_purged(removed)
        with self._mu:
            rec = self._pods.get(pod)
            if rec is not None:
                rec.purged_entries += removed
        logger.warning(
            "quarantined stale pod %s: purged %d index entr%s",
            pod, removed, "y" if removed == 1 else "ies",
        )
        self._fire_departed(pod)

    def _fire_departed(self, pod: str) -> None:
        cb = self.on_departed
        if cb is None:
            return
        try:
            cb(pod)
        except Exception as e:  # noqa: BLE001 - the reap fan-out must
            # never unwind the state machine that detected the departure
            logger.warning("on_departed callback failed for %s: %s", pod, e)

    def quarantine(self, pod_identifier: str) -> int:
        """Force a pod stale and purge its index entries now. Returns the
        number of pod entries removed from the index."""
        now = self.clock()
        with self._mu:
            rec = self._pods.get(pod_identifier)
            if rec is None:
                rec = _PodRecord(now)
                # Backdate so the lazy state machine agrees it is stale.
                rec.last_event_t = now - self.config.stale_after_s
                self._pods[pod_identifier] = rec
            if rec.state != STALE:
                self._transition(rec, pod_identifier, STALE, now)
                rec.stale_detected_at = now
        if self.index is None:
            self._fire_departed(pod_identifier)
            return 0
        removed = self.index.remove_pod(pod_identifier)
        metrics.count_stale_purged(removed)
        with self._mu:
            rec = self._pods.get(pod_identifier)
            if rec is not None:
                rec.purged_entries += removed
        self._fire_departed(pod_identifier)
        return removed

    def forget_pod(self, pod_identifier: str) -> int:
        """Drop every record belonging to a departed pod — all DP-rank-
        qualified variants of its base identity, plus its transfer-peer
        breaker rows (peer host == base identity). The tracker re-learns
        a returning pod from its first decoded batch; forgetting costs
        anomaly history, never correctness. Returns rows removed."""
        base = base_pod_identifier(pod_identifier)
        removed = 0
        with self._mu:
            for key in [
                k for k in self._pods if base_pod_identifier(k) == base
            ]:
                del self._pods[key]
                removed += 1
            for peer in [
                p for p in self._transfer_peers
                if p.rsplit(":", 1)[0] == base
            ]:
                del self._transfer_peers[peer]
                removed += 1
        if removed:
            logger.info(
                "forgot departed pod %s: %d fleet-health row(s)",
                pod_identifier, removed,
            )
        return removed

    def entries(self) -> int:
        """Tracked per-pod + per-peer rows — the resource accountant's
        O(1) meter read."""
        with self._mu:
            return len(self._pods) + len(self._transfer_peers)

    def state_of(self, pod_identifier: str, now: Optional[float] = None) -> str:
        """Current state; pods the tracker has never seen are healthy (an
        absent stream is no evidence against a pod that never stored)."""
        if now is None:
            now = self.clock()
        self.refresh(now)
        with self._mu:
            rec = self._pods.get(pod_identifier)
            return rec.state if rec is not None else HEALTHY

    # -- read-path hook ----------------------------------------------------

    def filter_scores(self, scores: Dict[str, float]) -> Dict[str, float]:
        """Demote suspect pods, exclude stale pods; healthy pass untouched.

        On an all-healthy fleet this returns `scores` unchanged (the same
        dict object — zero overhead, bit-identical routing). An emptied map
        is the explicit no-cache-signal answer: the caller's load fallback
        takes over instead of phantom placements.
        """
        if not scores:
            return scores
        self.refresh()
        factor = self.config.suspect_demotion_factor
        with self._mu:
            demoted: Optional[Dict[str, float]] = None
            for pod in scores:
                rec = self._pods.get(pod)
                if rec is None or rec.state == HEALTHY:
                    continue
                if demoted is None:
                    demoted = dict(scores)
                if rec.state == STALE:
                    del demoted[pod]
                else:  # SUSPECT
                    demoted[pod] = demoted[pod] * factor
        return scores if demoted is None else demoted

    def score_factors(self, pod_identifiers) -> tuple:
        """Per-pod demotion modes for the native scoring core.

        Returns ``(modes, suspect_demotion_factor)`` where ``modes`` is a
        bytes object aligned with `pod_identifiers` (0 healthy/unknown,
        1 suspect, 2 stale), or ``(None, factor)`` when the tracker has
        seen no pods (the all-healthy zero-overhead path). ``None``
        entries in the input are skipped (the interner's id-0 sentinel).

        Modes come from `_expected_state` WITHOUT advancing the state
        machine: `refresh()` transitions land exactly on the expected
        state, so the demote/drop decisions are identical to
        `filter_scores` — but auto-quarantine purges must not run before
        the caller's fused lookup+score crossing (the Python batch path
        does every lookup before its first `filter_scores`). The caller
        runs the real `refresh()` after the crossing.
        """
        factor = self.config.suspect_demotion_factor
        now = self.clock()
        with self._mu:
            if not self._pods:
                return None, factor
            modes = bytearray(len(pod_identifiers))
            for i, pod in enumerate(pod_identifiers):
                if pod is None:
                    continue
                rec = self._pods.get(pod)
                if rec is None:
                    continue
                expected = self._expected_state(rec, now)
                if expected == STALE:
                    modes[i] = 2
                elif expected == SUSPECT:
                    modes[i] = 1
        return bytes(modes), factor

    # -- introspection -----------------------------------------------------

    def summary(self, now: Optional[float] = None) -> dict:
        """Fleet-health snapshot for /readyz and the fault bench artifact."""
        if now is None:
            now = self.clock()
        self.refresh(now)
        with self._mu:
            pods = {}
            counts = {HEALTHY: 0, SUSPECT: 0, STALE: 0}
            for pod, rec in sorted(self._pods.items()):
                d = rec.as_dict()
                d["last_event_age_s"] = round(now - rec.last_event_t, 3)
                pods[pod] = d
                counts[rec.state] += 1
            out = {
                "pods": pods,
                "counts": counts,
                "subscriber": {
                    "connected": self._subscriber_connected,
                    "consecutive_failures": self._subscriber_failures,
                },
            }
            if self._transfer_peers:
                out["transfer_breakers"] = {
                    peer: dict(rec)
                    for peer, rec in sorted(self._transfer_peers.items())
                }
            return out

    def seq_snapshot(self) -> Dict[str, Dict[str, int]]:
        """Per-(pod, topic) last-applied wire seq: {pod: {topic: seq}}.

        The replication counters (cluster/snapshot.py): a snapshot stores
        them next to the index view so a restarted replica can replay only
        the event tail — anything at-or-below these watermarks is already
        inside the imported view. Pods whose transport carries no seq are
        absent.
        """
        with self._mu:
            return {
                pod: dict(rec.last_seq)
                for pod, rec in self._pods.items()
                if rec.last_seq
            }

    def anomaly_totals(self) -> dict:
        with self._mu:
            return {
                "seq_gaps": sum(r.seq_gaps for r in self._pods.values()),
                "gap_events": sum(r.gap_events for r in self._pods.values()),
                "duplicates": sum(r.duplicates for r in self._pods.values()),
                "reorders": sum(r.reorders for r in self._pods.values()),
                "ts_regressions": sum(
                    r.ts_regressions for r in self._pods.values()
                ),
                "decode_failures": sum(
                    r.decode_failures for r in self._pods.values()
                ),
            }

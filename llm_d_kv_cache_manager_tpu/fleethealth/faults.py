"""Deterministic fault injection for the KV-event write plane.

Faults are injected at the pod→pool delivery seam (the same place the
bench's event sink and the ZMQ subscriber hand `Message`s to
`EventPool.add_task`), so everything downstream — decode, sharding,
digest, liveness tracking — is the REAL code path under test. The
injector never inspects payloads; it only drops, duplicates, holds, and
swaps whole messages, exactly what a crashed pod, a stalled stream, or a
lossy/reordering transport does.

Fault classes (per pod, composable):

- **crash / restart**: every message in ``[crash_at_s, restart_at_s)`` is
  swallowed (the pod is gone; nothing publishes). The bench additionally
  stops *serving* on the pod and replaces it with a cold instance at
  restart.
- **stall**: messages in ``[stall_from_s, stall_until_s)`` are swallowed —
  a wedged publisher/subscriber whose overflow is dropped. The pod keeps
  serving; the index's view of it silently freezes.
- **drop_rate**: each message is independently lost with this probability
  (seeded RNG) — the receiver sees seq gaps.
- **duplicate_rate**: the message is delivered twice, same seq.
- **reorder_rate**: the message is held and delivered AFTER the pod's
  next message — adjacent swap, the receiver sees seq go backwards.

Everything is driven by an injected clock and a seeded RNG: a fault run
is a pure function of (plan, workload), replayable bit-for-bit.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional


@dataclass
class PodFaults:
    crash_at_s: Optional[float] = None
    restart_at_s: Optional[float] = None  # None with crash_at_s = stays dead
    stall_from_s: Optional[float] = None
    stall_until_s: Optional[float] = None
    drop_rate: float = 0.0
    duplicate_rate: float = 0.0
    reorder_rate: float = 0.0

    def crashed(self, now: float) -> bool:
        if self.crash_at_s is None or now < self.crash_at_s:
            return False
        return self.restart_at_s is None or now < self.restart_at_s

    def stalled(self, now: float) -> bool:
        return (
            self.stall_from_s is not None
            and self.stall_from_s <= now
            and (self.stall_until_s is None or now < self.stall_until_s)
        )


@dataclass
class FaultPlan:
    seed: int = 0
    pods: Dict[str, PodFaults] = field(default_factory=dict)
    # Indexer (control-plane) fault: the index service itself dies at
    # crash_at and comes back at restart_at. While down, nothing digests
    # events and nothing answers scoring calls — the replicated control
    # plane's whole reason to exist. The bench's two recovery arms differ
    # in what the restarted instance starts FROM: an empty index (cold) or
    # a snapshot + seq-tail replay (warm, cluster/snapshot.py).
    indexer_crash_at_s: Optional[float] = None
    indexer_restart_at_s: Optional[float] = None

    def for_pod(self, pod_id: str) -> Optional[PodFaults]:
        return self.pods.get(pod_id)

    def indexer_crashed(self, now: float) -> bool:
        if self.indexer_crash_at_s is None or now < self.indexer_crash_at_s:
            return False
        return (
            self.indexer_restart_at_s is None
            or now < self.indexer_restart_at_s
        )

    def as_dict(self) -> dict:
        """JSON-serializable provenance for bench artifacts."""
        out: Dict[str, dict] = {}
        for pod, f in sorted(self.pods.items()):
            out[pod] = {
                k: v
                for k, v in (
                    ("crash_at_s", f.crash_at_s),
                    ("restart_at_s", f.restart_at_s),
                    ("stall_from_s", f.stall_from_s),
                    ("stall_until_s", f.stall_until_s),
                    ("drop_rate", f.drop_rate),
                    ("duplicate_rate", f.duplicate_rate),
                    ("reorder_rate", f.reorder_rate),
                )
                if v not in (None, 0.0)
            }
        doc = {"seed": self.seed, "pods": out}
        if self.indexer_crash_at_s is not None:
            doc["indexer"] = {
                "crash_at_s": self.indexer_crash_at_s,
                "restart_at_s": self.indexer_restart_at_s,
            }
        return doc


class FaultInjector:
    """Applies a FaultPlan at the message-delivery seam.

    `wrap(pod_id, deliver)` returns a delivery callable with the pod's
    faults applied; pods without planned faults get `deliver` back
    unwrapped (zero overhead — the no-fault path stays bit-identical).
    """

    def __init__(self, plan: FaultPlan, clock: Callable[[], float]):
        self.plan = plan
        self.clock = clock
        self._rng = random.Random(plan.seed)
        # pod -> (message awaiting swap, its delivery callable)
        self._held: Dict[str, tuple] = {}
        self.injected = {
            "crash_dropped": 0,
            "stall_dropped": 0,
            "dropped": 0,
            "duplicated": 0,
            "reordered": 0,
        }

    def wrap(self, pod_id: str, deliver: Callable) -> Callable:
        faults = self.plan.for_pod(pod_id)
        if faults is None:
            return deliver

        def delivery(msg):
            now = self.clock()
            if faults.crashed(now):
                self.injected["crash_dropped"] += 1
                return
            if faults.stalled(now):
                self.injected["stall_dropped"] += 1
                return
            if faults.drop_rate and self._rng.random() < faults.drop_rate:
                self.injected["dropped"] += 1
                return
            if faults.reorder_rate:
                held = self._held.pop(pod_id, None)
                if held is not None:
                    # Second half of an adjacent swap: newer first.
                    deliver(msg)
                    held[1](held[0])
                    self.injected["reordered"] += 1
                    return
                if self._rng.random() < faults.reorder_rate:
                    self._held[pod_id] = (msg, deliver)
                    return
            deliver(msg)
            if faults.duplicate_rate and self._rng.random() < faults.duplicate_rate:
                deliver(msg)
                self.injected["duplicated"] += 1

        return delivery

    def flush(self) -> None:
        """Deliver any message still held for a reorder swap (end of run —
        a real transport would eventually flush its buffer too)."""
        held, self._held = self._held, {}
        for msg, deliver in held.values():
            deliver(msg)

    def held_count(self) -> int:
        return len(self._held)

"""Deterministic fault injection for the KV-event write plane.

Faults are injected at the pod→pool delivery seam (the same place the
bench's event sink and the ZMQ subscriber hand `Message`s to
`EventPool.add_task`), so everything downstream — decode, sharding,
digest, liveness tracking — is the REAL code path under test. The
injector never inspects payloads; it only drops, duplicates, holds, and
swaps whole messages, exactly what a crashed pod, a stalled stream, or a
lossy/reordering transport does.

Fault classes (per pod, composable):

- **crash / restart**: every message in ``[crash_at_s, restart_at_s)`` is
  swallowed (the pod is gone; nothing publishes). The bench additionally
  stops *serving* on the pod and replaces it with a cold instance at
  restart.
- **stall**: messages in ``[stall_from_s, stall_until_s)`` are swallowed —
  a wedged publisher/subscriber whose overflow is dropped. The pod keeps
  serving; the index's view of it silently freezes.
- **drop_rate**: each message is independently lost with this probability
  (seeded RNG) — the receiver sees seq gaps.
- **duplicate_rate**: the message is delivered twice, same seq.
- **reorder_rate**: the message is held and delivered AFTER the pod's
  next message — adjacent swap, the receiver sees seq go backwards.

Silent-divergence modes (antientropy/ — PR 15). Unlike the classes above,
these corrupt the index's CONTENT while the stream stays perfectly
healthy (no gap, no silence — nothing fleethealth can see), which is the
failure family the anti-entropy loop exists to heal:

- **silent_wipe_at_s**: the pod loses its cache at this instant (engine
  restart whose removal events were lost) but keeps publishing and
  serving seamlessly — every pre-wipe index entry becomes phantom. The
  bench owns the cache replacement; the field here is the plan's
  declarative record of it.
- **phantom_advertise_rate / phantom_from_s / phantom_until_s**: a buggy
  engine advertising blocks it never holds. After each of the pod's own
  deliveries in the window, with this probability a recently-seen
  BlockStored message from ANOTHER pod is re-delivered re-attributed to
  this pod (seq=None — the phantom stream carries no sequence, so it
  cannot masquerade as gap evidence): the index learns placements the
  pod cannot serve. These two modes decode payloads (the donor ring must
  recognize BlockStored) — the only modes that do.

Everything is driven by an injected clock and a seeded RNG: a fault run
is a pure function of (plan, workload), replayable bit-for-bit.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional


@dataclass
class PodFaults:
    crash_at_s: Optional[float] = None
    restart_at_s: Optional[float] = None  # None with crash_at_s = stays dead
    stall_from_s: Optional[float] = None
    stall_until_s: Optional[float] = None
    drop_rate: float = 0.0
    duplicate_rate: float = 0.0
    reorder_rate: float = 0.0
    # Silent-divergence modes (module docstring): a cache loss the event
    # stream never reports (one-shot at silent_wipe_at_s; recurring every
    # silent_wipe_every_s until silent_wipe_until_s when every > 0 — the
    # "leaky cache layer" shape), and a phantom-advertisement window.
    silent_wipe_at_s: Optional[float] = None
    silent_wipe_every_s: float = 0.0
    silent_wipe_until_s: Optional[float] = None
    phantom_advertise_rate: float = 0.0
    phantom_from_s: Optional[float] = None
    phantom_until_s: Optional[float] = None

    def phantom_active(self, now: float) -> bool:
        if self.phantom_advertise_rate <= 0.0:
            return False
        if self.phantom_from_s is not None and now < self.phantom_from_s:
            return False
        return self.phantom_until_s is None or now < self.phantom_until_s

    def crashed(self, now: float) -> bool:
        if self.crash_at_s is None or now < self.crash_at_s:
            return False
        return self.restart_at_s is None or now < self.restart_at_s

    def stalled(self, now: float) -> bool:
        return (
            self.stall_from_s is not None
            and self.stall_from_s <= now
            and (self.stall_until_s is None or now < self.stall_until_s)
        )


@dataclass
class FaultPlan:
    seed: int = 0
    pods: Dict[str, PodFaults] = field(default_factory=dict)
    # Indexer (control-plane) fault: the index service itself dies at
    # crash_at and comes back at restart_at. While down, nothing digests
    # events and nothing answers scoring calls — the replicated control
    # plane's whole reason to exist. The bench's two recovery arms differ
    # in what the restarted instance starts FROM: an empty index (cold) or
    # a snapshot + seq-tail replay (warm, cluster/snapshot.py).
    indexer_crash_at_s: Optional[float] = None
    indexer_restart_at_s: Optional[float] = None

    def for_pod(self, pod_id: str) -> Optional[PodFaults]:
        return self.pods.get(pod_id)

    def indexer_crashed(self, now: float) -> bool:
        if self.indexer_crash_at_s is None or now < self.indexer_crash_at_s:
            return False
        return (
            self.indexer_restart_at_s is None
            or now < self.indexer_restart_at_s
        )

    def as_dict(self) -> dict:
        """JSON-serializable provenance for bench artifacts."""
        out: Dict[str, dict] = {}
        for pod, f in sorted(self.pods.items()):
            out[pod] = {
                k: v
                for k, v in (
                    ("crash_at_s", f.crash_at_s),
                    ("restart_at_s", f.restart_at_s),
                    ("stall_from_s", f.stall_from_s),
                    ("stall_until_s", f.stall_until_s),
                    ("drop_rate", f.drop_rate),
                    ("duplicate_rate", f.duplicate_rate),
                    ("reorder_rate", f.reorder_rate),
                    ("silent_wipe_at_s", f.silent_wipe_at_s),
                    ("silent_wipe_every_s", f.silent_wipe_every_s),
                    ("silent_wipe_until_s", f.silent_wipe_until_s),
                    ("phantom_advertise_rate", f.phantom_advertise_rate),
                    ("phantom_from_s", f.phantom_from_s),
                    ("phantom_until_s", f.phantom_until_s),
                )
                if v not in (None, 0.0)
            }
        doc = {"seed": self.seed, "pods": out}
        if self.indexer_crash_at_s is not None:
            doc["indexer"] = {
                "crash_at_s": self.indexer_crash_at_s,
                "restart_at_s": self.indexer_restart_at_s,
            }
        return doc


class FaultInjector:
    """Applies a FaultPlan at the message-delivery seam.

    `wrap(pod_id, deliver)` returns a delivery callable with the pod's
    faults applied; pods without planned faults get `deliver` back
    unwrapped (zero overhead — the no-fault path stays bit-identical).
    """

    def __init__(self, plan: FaultPlan, clock: Callable[[], float]):
        self.plan = plan
        self.clock = clock
        self._rng = random.Random(plan.seed)
        # pod -> (message awaiting swap, its delivery callable)
        self._held: Dict[str, tuple] = {}
        # Phantom-advertiser donor ring: recent BlockStored-carrying
        # messages from NON-phantom pods, recorded only when the plan
        # actually contains a phantom mode (pods without planned faults
        # otherwise stay unwrapped — zero overhead).
        self._phantom_in_plan = any(
            f.phantom_advertise_rate > 0.0 for f in plan.pods.values()
        )
        self._donor_ring: list = []
        self._donor_cap = 64
        self.injected = {
            "crash_dropped": 0,
            "stall_dropped": 0,
            "dropped": 0,
            "duplicated": 0,
            "reordered": 0,
            "phantom_advertised": 0,
        }

    def _record_donor(self, msg) -> None:
        """Admit a store-carrying message to the donor ring (decodes the
        payload — acceptable in the sim's fault arms, and only reached
        when a phantom mode is planned). Host-tier stores are tagged:
        they are the FETCHABLE advertisements (the data plane only pulls
        staged blocks), so the phantom pick prefers them — a phantom
        device-tier entry misleads only scoring, a phantom host-tier
        entry also sells fetches that can never land."""
        from llm_d_kv_cache_manager_tpu.kvevents.events import (
            BlockStored,
            EventBatch,
        )

        try:
            batch = EventBatch.from_msgpack(msg.payload)
        except Exception:  # noqa: BLE001 - poison pills make poor donors
            return
        stores = [e for e in batch.events if isinstance(e, BlockStored)]
        if not stores:
            return
        hosty = any(
            (e.medium or "").lower() in ("host", "cpu") for e in stores
        )
        self._donor_ring.append((msg, hosty))
        if len(self._donor_ring) > self._donor_cap:
            self._donor_ring.pop(0)

    def _phantom_copy(self, pod_id: str):
        """A seeded donor pick re-attributed to `pod_id`: the phantom
        advertisement (blocks another pod computed, claimed by this one),
        host-tier donors preferred (see _record_donor). seq=None — the
        phantom stream must not double as seq-gap noise."""
        import dataclasses

        donors = [
            (m, hosty) for m, hosty in self._donor_ring
            if m.pod_identifier != pod_id
        ]
        if not donors:
            return None
        host_donors = [m for m, hosty in donors if hosty]
        pool = host_donors if host_donors else [m for m, _h in donors]
        donor = pool[self._rng.randrange(len(pool))]
        return dataclasses.replace(
            donor,
            pod_identifier=pod_id,
            topic=f"kv@{pod_id}@{donor.model_name}",
            seq=None,
            enqueue_t=0.0,
        )

    def wrap(self, pod_id: str, deliver: Callable) -> Callable:
        faults = self.plan.for_pod(pod_id)
        if faults is None:
            if not self._phantom_in_plan:
                return deliver

            # Donor-only wrapper: healthy pods feed the phantom ring.
            def recording_delivery(msg):
                self._record_donor(msg)
                deliver(msg)

            return recording_delivery

        def delivery(msg):
            now = self.clock()
            if faults.crashed(now):
                self.injected["crash_dropped"] += 1
                return
            if faults.stalled(now):
                self.injected["stall_dropped"] += 1
                return
            if faults.drop_rate and self._rng.random() < faults.drop_rate:
                self.injected["dropped"] += 1
                return
            if self._phantom_in_plan and faults.phantom_advertise_rate <= 0.0:
                self._record_donor(msg)
            if faults.reorder_rate:
                held = self._held.pop(pod_id, None)
                if held is not None:
                    # Second half of an adjacent swap: newer first.
                    deliver(msg)
                    held[1](held[0])
                    self.injected["reordered"] += 1
                    return
                if self._rng.random() < faults.reorder_rate:
                    self._held[pod_id] = (msg, deliver)
                    return
            deliver(msg)
            if faults.duplicate_rate and self._rng.random() < faults.duplicate_rate:
                deliver(msg)
                self.injected["duplicated"] += 1
            if (
                faults.phantom_active(now)
                and self._rng.random() < faults.phantom_advertise_rate
            ):
                phantom = self._phantom_copy(pod_id)
                if phantom is not None:
                    deliver(phantom)
                    self.injected["phantom_advertised"] += 1

        return delivery

    def flush(self) -> None:
        """Deliver any message still held for a reorder swap (end of run —
        a real transport would eventually flush its buffer too)."""
        held, self._held = self._held, {}
        for msg, deliver in held.values():
            deliver(msg)

    def held_count(self) -> int:
        return len(self._held)

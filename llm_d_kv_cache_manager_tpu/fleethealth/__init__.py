from llm_d_kv_cache_manager_tpu.fleethealth.faults import (
    FaultInjector,
    FaultPlan,
    PodFaults,
)
from llm_d_kv_cache_manager_tpu.fleethealth.load import (
    PodLoad,
    PodLoadConfig,
    PodLoadTracker,
)
from llm_d_kv_cache_manager_tpu.fleethealth.tracker import (
    HEALTHY,
    STALE,
    SUSPECT,
    FleetHealthConfig,
    FleetHealthTracker,
)

__all__ = [
    "FaultInjector",
    "FaultPlan",
    "FleetHealthConfig",
    "FleetHealthTracker",
    "HEALTHY",
    "PodFaults",
    "PodLoad",
    "PodLoadConfig",
    "PodLoadTracker",
    "STALE",
    "SUSPECT",
]

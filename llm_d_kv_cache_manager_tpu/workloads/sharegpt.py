"""Deterministic ShareGPT-shaped multi-turn session generator.

Produces a `WorkloadTrace` whose empirical distributions match the
committed ShareGPT tables (workloads.tables): per-turn user prompt
lengths, assistant output lengths, turns-per-conversation, and the
shared-system-prefix mix. The mechanism that actually creates prefix-cache
hits — each conversation's prompt growing by concatenating its prior
turns — lives in `WorkloadTrace.materialize()` (workloads.spec), so the
sim bench and the device harness serve byte-identical prompt streams from
the same trace.

Everything is a pure function of (config, seed): a single
`random.Random(seed)` drives every draw in a fixed order, so two
generations with equal configs are equal traces — the determinism the
record/replay contract (workloads.trace) is built on.

Arrivals are OPEN-LOOP (workloads.arrivals): session starts follow a
Poisson or bursty ON-OFF process; a session's later turns follow its
previous turn after an exponential per-session think time plus a
read-time term proportional to the previous response's length. Arrival
times never depend on measured service times — the bench's queue is
allowed to actually build.
"""

from __future__ import annotations

import dataclasses
import random
from dataclasses import dataclass
from typing import Optional

from llm_d_kv_cache_manager_tpu.workloads import stats, tables
from llm_d_kv_cache_manager_tpu.workloads.arrivals import (
    arrival_process,
    think_time_s,
)
from llm_d_kv_cache_manager_tpu.workloads.spec import TraceTurn, WorkloadTrace
from llm_d_kv_cache_manager_tpu.workloads.synthetic import text as _text


@dataclass(frozen=True)
class ShareGPTConfig:
    """Knobs of the generator; the whole dataclass is recorded in the
    trace header (provenance) and round-trips through JSONL."""

    n_sessions: int = 48
    seed: int = 42
    # Session-start arrival process ("poisson" | "bursty") and rate.
    arrival: str = "poisson"
    session_rate_per_s: float = 1.5
    burst_on_s: float = 10.0
    burst_off_s: float = 20.0
    # Per-session think time between turns.
    think_time_mean_s: float = 6.0
    read_s_per_unit: float = 0.01
    # Shared-system-prefix mix (tables.SYSTEM_PREFIX_SHARE by default).
    system_prefix_share: float = tables.SYSTEM_PREFIX_SHARE
    prefix_groups: int = 8
    # Optional truncations for bounded bench runs; None = table-faithful.
    max_turns: Optional[int] = None
    # Scales every sampled length (smoke/CI configs shrink the workload
    # without changing its shape); 1.0 = table-faithful.
    length_scale: float = 1.0

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def generate(config: Optional[ShareGPTConfig] = None) -> WorkloadTrace:
    """Build the trace: sessions, scripted responses, open-loop arrivals."""
    cfg = config or ShareGPTConfig()
    if not 0.0 <= cfg.system_prefix_share <= 1.0:
        raise ValueError(
            f"system_prefix_share must be in [0,1], got {cfg.system_prefix_share}"
        )
    if cfg.prefix_groups <= 0 and cfg.system_prefix_share > 0:
        raise ValueError("prefix_groups must be >= 1 when prefixes are on")
    rng = random.Random(cfg.seed)

    # Group prefixes first (fixed draw order = determinism): each group's
    # shared system prompt, length from the committed prefix table.
    group_prefixes = []
    for g in range(cfg.prefix_groups if cfg.system_prefix_share > 0 else 0):
        n = stats.sample_length(
            rng, tables.SYSTEM_PREFIX_LEN_QUANTILES, cfg.length_scale
        )
        group_prefixes.append(f"[group {g}] " + _text(rng, n))

    starts = arrival_process(
        cfg.arrival, rng, cfg.session_rate_per_s,
        on_s=cfg.burst_on_s, off_s=cfg.burst_off_s,
    )

    sessions = {}
    turns = []
    for s in range(cfg.n_sessions):
        session_id = f"s{s}"
        start = next(starts)
        if group_prefixes and rng.random() < cfg.system_prefix_share:
            sessions[session_id] = group_prefixes[
                rng.randrange(len(group_prefixes))
            ]
        else:
            sessions[session_id] = ""
        n_turns = stats.sample_pmf(rng, tables.TURNS_PER_SESSION_PMF)
        if cfg.max_turns is not None:
            n_turns = min(n_turns, cfg.max_turns)
        arrival = start
        for t in range(n_turns):
            user_len = stats.sample_length(
                rng, tables.USER_LEN_QUANTILES, cfg.length_scale
            )
            output_len = stats.sample_length(
                rng, tables.OUTPUT_LEN_QUANTILES, cfg.length_scale
            )
            turns.append(TraceTurn(
                arrival_s=round(arrival, 6),
                session=session_id,
                turn=t,
                user_len=user_len,
                output_len=output_len,
                user_text=_text(rng, user_len),
                response_text=_text(rng, output_len),
            ))
            arrival += think_time_s(
                rng, cfg.think_time_mean_s, output_len, cfg.read_s_per_unit
            )

    # Arrival order with a total, deterministic tie-break.
    turns.sort(key=lambda t: (t.arrival_s, t.session, t.turn))
    return WorkloadTrace(
        workload="sharegpt",
        seed=cfg.seed,
        config=cfg.as_dict(),
        tables_version=tables.TABLES_VERSION,
        sessions=sessions,
        turns=turns,
    )


def uniform_control(config: Optional[ShareGPTConfig] = None) -> WorkloadTrace:
    """Single-turn, prefix-free control at the same lengths/arrivals: the
    workload with the multi-turn growth (and shared prefixes) removed.
    Comparing a bench's hit rate on `generate()` vs this control isolates
    what prefix reuse — the thing the index exists for — is worth."""
    cfg = config or ShareGPTConfig()
    cfg = dataclasses.replace(cfg, system_prefix_share=0.0, max_turns=1)
    trace = generate(cfg)
    return dataclasses.replace(trace, workload="sharegpt-uniform-control")

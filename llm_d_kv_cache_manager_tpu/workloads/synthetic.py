"""Synthetic-workload text: the `synthetic` backend of the workload engine.

Both the modeled fleet bench (bench.py) and the real-compute mini-fleet
bench (benchmarking/fleet_device_bench.py) default to the same multi-turn
shared-system-prompt synthetic workload shape; their TTFT/hit-rate numbers
are meant to be read against each other, so the text machinery lives here
once — tuning it in one bench without the other silently breaking the
comparison is exactly the drift this module prevents. The ShareGPT-shaped
generator (workloads.sharegpt) draws its turn/response text from the same
vocabulary, so synthetic vs sharegpt comparisons differ only in
*distribution*, never in token inventory.

Historically this lived at utils/workload.py; that module remains as a
re-export shim so existing imports keep working.
"""

from __future__ import annotations

import random

WORDS = (
    "the quick brown fox jumps over lazy dog system user assistant tool "
    "response message conversation template routing cache block prefix "
    "token mesh shard kernel attention page table fleet score index event"
).split()


def text(rng: random.Random, n_words: int) -> str:
    return " ".join(rng.choice(WORDS) for _ in range(n_words))


def shared_prefix_conversations(
    rng: random.Random, n_groups: int, users_per_group: int, system_words: int
) -> dict:
    """{conv_id: history}: each group's users share one system prompt —
    the prefix-reuse structure of the reference's capacity benchmarks."""
    system_prompts = [
        f"[group {g}] " + text(rng, system_words) for g in range(n_groups)
    ]
    return {
        f"g{g}-u{u}": system_prompts[g]
        for g in range(n_groups)
        for u in range(users_per_group)
    }

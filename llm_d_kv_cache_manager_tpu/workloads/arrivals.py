"""Open-loop arrival processes for the workload engine.

Open-loop means the arrival stream is scripted up-front and never depends
on measured service times — the regime where routing quality compounds
through queueing (the reference's headline runs; a closed-loop driver can
never overload a pod). Two session-arrival processes:

- "poisson": memoryless arrivals at a constant rate — the steady-traffic
  baseline every queueing result assumes.
- "bursty": an ON-OFF modulated Poisson process (interrupted Poisson):
  arrivals at an elevated rate during ON windows, silence during OFF.
  The ON rate is scaled so the long-run mean rate equals `rate`, which
  makes poisson-vs-bursty comparisons at equal offered load meaningful.

Per-session think time (the gap between a response and the same user's
next message) is exponential around a mean, plus a read-time term
proportional to the response length — a user who received 800 tokens
replies later than one who received 20.
"""

from __future__ import annotations

import random
from typing import Iterator


def poisson_arrivals(rng: random.Random, rate_per_s: float) -> Iterator[float]:
    """Infinite stream of absolute arrival times at `rate_per_s`."""
    if rate_per_s <= 0:
        raise ValueError(f"rate_per_s must be > 0, got {rate_per_s}")
    t = 0.0
    while True:
        t += rng.expovariate(rate_per_s)
        yield t


def on_off_arrivals(
    rng: random.Random,
    rate_per_s: float,
    on_s: float = 10.0,
    off_s: float = 20.0,
) -> Iterator[float]:
    """Interrupted-Poisson arrivals: Poisson bursts during ON windows of
    `on_s` seconds, nothing during OFF windows of `off_s` seconds. The
    burst rate is `rate * (on+off)/on`, so the long-run mean equals the
    plain Poisson process at the same `rate_per_s`."""
    if rate_per_s <= 0:
        raise ValueError(f"rate_per_s must be > 0, got {rate_per_s}")
    if on_s <= 0 or off_s < 0:
        raise ValueError(f"invalid ON/OFF durations: on={on_s} off={off_s}")
    burst_rate = rate_per_s * (on_s + off_s) / on_s
    window_start = 0.0
    t = 0.0
    while True:
        window_end = window_start + on_s
        t = max(t, window_start)
        while True:
            t += rng.expovariate(burst_rate)
            if t >= window_end:
                break
            yield t
        window_start = window_end + off_s


def arrival_process(
    name: str,
    rng: random.Random,
    rate_per_s: float,
    on_s: float = 10.0,
    off_s: float = 20.0,
) -> Iterator[float]:
    if name == "poisson":
        return poisson_arrivals(rng, rate_per_s)
    if name == "bursty":
        return on_off_arrivals(rng, rate_per_s, on_s=on_s, off_s=off_s)
    raise ValueError(f"unknown arrival process: {name!r}")


def think_time_s(
    rng: random.Random,
    mean_s: float,
    response_len: int,
    read_s_per_unit: float,
) -> float:
    """Gap between receiving a response and sending the next message."""
    gap = rng.expovariate(1.0 / mean_s) if mean_s > 0 else 0.0
    return gap + read_s_per_unit * max(int(response_len), 0)

"""Vendored ShareGPT length/turn distribution tables.

The BASELINE north-star metric is defined over a **ShareGPT replay**
(prefix-cache hit rate + p50 TTFT on a vLLM-TPU fleet), so the workload
engine needs the ShareGPT *shape* without any network egress. These tables
are a committed reconstruction of the ShareGPT_V3_unfiltered_cleaned_split
summary statistics as reported by the vLLM serving benchmarks (the dataset
vLLM's benchmark_serving.py samples: per-turn user prompts with a ~28-token
median and a long tail past 1k tokens; assistant outputs with a ~170-token
median, both truncated near the 2048-token context of the original
captures; most conversations short, with a tail of 10-30+ turn chats).
They are approximations of published aggregates — NOT a verbatim dump of
the dataset (which is not redistributable here) — and are versioned so a
regenerated table can be told apart from this one.

Length unit: "length units" — the generator emits that many synthetic
WORDS (workloads.synthetic.text). The test fixture's BPE maps one word to
a few tokens, which inflates absolute token counts by a roughly constant
factor but preserves the distribution *shape* — and every consumer of
these numbers (bench.py's TTFT model, the device bench's prefill) works in
the same unit on both sides of a comparison, so the shape is what matters.

The shared-system-prefix mix is the one deliberate departure from raw
ShareGPT: the raw captures carry almost no standing system prompts, but
the production fleets the reference benchmarks (37-capacity/73-capacity:
8k/6k-token shared prefixes) are dominated by them. `SYSTEM_PREFIX_SHARE`
and `SYSTEM_PREFIX_LEN_QUANTILES` graft that reference-benchmark prefix
structure onto the ShareGPT turn/length shape; set the share to 0.0 for a
prefix-free raw-ShareGPT workload.

Quantile tables are (quantile, value) pairs defining a piecewise-linear
inverse CDF (workloads.stats interpolates between them); the turn count is
a small-integer pmf instead, because a handful of discrete values carries
the mass.
"""

from __future__ import annotations

TABLES_VERSION = "sharegpt-v1"

# Per-turn USER message length (length units ≈ tokens). Median ~28, long
# tail to the 2048-token truncation of the source captures.
USER_LEN_QUANTILES: tuple = (
    (0.00, 1),
    (0.10, 6),
    (0.25, 12),
    (0.50, 28),
    (0.75, 80),
    (0.90, 240),
    (0.95, 480),
    (0.99, 1300),
    (1.00, 2048),
)

# Per-turn ASSISTANT output length (length units ≈ tokens). Median ~170,
# mean ~230 — ShareGPT outputs run much longer than its prompts.
OUTPUT_LEN_QUANTILES: tuple = (
    (0.00, 1),
    (0.10, 20),
    (0.25, 62),
    (0.50, 170),
    (0.75, 350),
    (0.90, 580),
    (0.95, 750),
    (0.99, 1100),
    (1.00, 2048),
)

# USER turns per conversation: pmf over the discrete counts that carry the
# mass. Mean ≈ 4.0 turns; ~10% of chats run 10 turns or longer — the
# multi-turn tail is what makes prefix reuse compound.
TURNS_PER_SESSION_PMF: tuple = (
    (1, 0.32),
    (2, 0.17),
    (3, 0.12),
    (4, 0.09),
    (5, 0.07),
    (6, 0.055),
    (7, 0.04),
    (8, 0.035),
    (10, 0.045),
    (12, 0.02),
    (16, 0.015),
    (20, 0.01),
    (24, 0.005),
    (32, 0.005),
)

# Shared-system-prefix mix (reference-benchmark graft, see module
# docstring): fraction of sessions that belong to a prefix group, and the
# length distribution of the group prefixes (up to the reference's
# 8k-token shared prefixes).
SYSTEM_PREFIX_SHARE = 0.55
SYSTEM_PREFIX_LEN_QUANTILES: tuple = (
    (0.00, 130),
    (0.25, 700),
    (0.50, 1600),
    (0.75, 3200),
    (0.90, 6000),
    (1.00, 8192),
)


def pmf_total(pmf) -> float:
    return sum(p for _v, p in pmf)


assert abs(pmf_total(TURNS_PER_SESSION_PMF) - 1.0) < 1e-9, (
    "TURNS_PER_SESSION_PMF must sum to 1"
)

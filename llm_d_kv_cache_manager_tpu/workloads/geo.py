"""Geo-distributed ShareGPT-shaped workload: home-pinned sessions under
diurnal skew.

The federation bench's scenario (ROADMAP "Hierarchical federation"):
millions of users span regions, every session lives in exactly one home
region (its user does not move mid-conversation), and *when* each region
is busy follows the sun — traffic peaks walk around the planet with a
phase offset per region. This generator produces that shape,
deterministically:

- every region gets `prefixes_per_region` shared system prompts (the
  regional tenants/products whose prefixes are the thing worth routing
  on); a session's prefix is drawn from its HOME region's set, so prefix
  affinity is a regional property by construction — exactly the signal
  the global tier's popularity sketches can see and a flat global fleet
  cannot exploit;
- session home regions are drawn from per-region **diurnal weights**
  evaluated at the session's start time: region r's weight is
  ``1 + amplitude * sin(2π * (t/day_period - r/R))``, so each region's
  sessions cluster in its own peak window (a compressed day —
  `day_period_s` of sim time — keeps the bench finite);
- turn counts and user/output lengths come from the same committed
  ShareGPT tables as every other generator; arrivals are open-loop with
  per-session think time.

Home pins are recorded per session in the trace (`session_regions`; the
JSONL `region` field on session records, workloads.trace) and surface on
every `MaterializedRequest.region` — so region identity survives the
record/replay round trip, and a pre-geo trace replays unchanged with
`region=None` everywhere. Losing a region mid-replay is a REPLAY-time
event (the bench kills the region's fleet at `--geo`'s loss time); the
trace itself is loss-free so one recording serves both the lossy and
loss-free arms.
"""

from __future__ import annotations

import dataclasses
import math
import random
from dataclasses import dataclass
from typing import List, Optional

from llm_d_kv_cache_manager_tpu.workloads import stats, tables
from llm_d_kv_cache_manager_tpu.workloads.arrivals import (
    arrival_process,
    think_time_s,
)
from llm_d_kv_cache_manager_tpu.workloads.spec import TraceTurn, WorkloadTrace
from llm_d_kv_cache_manager_tpu.workloads.synthetic import text as _text


@dataclass(frozen=True)
class GeoConfig:
    """Knobs of the geo generator (recorded in the trace header)."""

    n_regions: int = 3
    n_sessions: int = 120
    seed: int = 42
    # Diurnal model: one compressed "day" of `day_period_s` sim seconds;
    # region r's arrival weight peaks 1/R of a day after region r-1's.
    # amplitude=0 is the uniform control (no skew); 1.0 means a region's
    # trough receives (almost) no new sessions.
    day_period_s: float = 120.0
    diurnal_amplitude: float = 0.8
    # Session-start arrival process (global, before the region draw).
    arrival: str = "poisson"
    session_rate_per_s: float = 2.0
    burst_on_s: float = 10.0
    burst_off_s: float = 20.0
    think_time_mean_s: float = 4.0
    read_s_per_unit: float = 0.005
    # Regional shared prefixes: how many per region, and their length.
    # Fixed words (like the placement bench) so cross-arm dynamics measure
    # the GEOGRAPHY, not the prefix-length lottery; None draws from the
    # committed prefix-length table.
    prefixes_per_region: int = 2
    prefix_words: Optional[int] = 600
    prefix_length_scale: float = 1.0
    length_scale: float = 1.0
    # Turn cap (the pmf's marathon tail would let one session dominate).
    max_turns: Optional[int] = 5

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def region_name(r: int) -> str:
    return f"region-{r}"


def diurnal_weights(
    t: float, n_regions: int, day_period_s: float, amplitude: float
) -> List[float]:
    """Normalized per-region arrival weights at time `t`."""
    raw = [
        max(
            1.0 + amplitude * math.sin(
                2.0 * math.pi * (t / day_period_s - r / n_regions)
            ),
            0.0,
        )
        for r in range(n_regions)
    ]
    total = sum(raw)
    if total <= 0:  # amplitude > 1 could zero every region at some t
        return [1.0 / n_regions] * n_regions
    return [w / total for w in raw]


def generate(config: Optional[GeoConfig] = None) -> WorkloadTrace:
    """Build the geo trace. Deterministic in (config, seed)."""
    cfg = config or GeoConfig()
    if cfg.n_regions <= 0:
        raise ValueError("n_regions must be >= 1")
    if cfg.diurnal_amplitude < 0:
        raise ValueError("diurnal_amplitude must be >= 0")
    if cfg.day_period_s <= 0:
        raise ValueError("day_period_s must be > 0")
    rng = random.Random(cfg.seed)

    # Regional prefix pools first, in (region, slot) order (fixed draw
    # order — same discipline as the multi-tenant generator).
    prefixes: List[List[str]] = []
    for r in range(cfg.n_regions):
        pool = []
        for p in range(cfg.prefixes_per_region):
            n = cfg.prefix_words
            if n is None:
                n = stats.sample_length(
                    rng, tables.SYSTEM_PREFIX_LEN_QUANTILES,
                    cfg.prefix_length_scale,
                )
            pool.append(f"[{region_name(r)} tenant {p}] " + _text(rng, n))
        prefixes.append(pool)

    starts = arrival_process(
        cfg.arrival, rng, cfg.session_rate_per_s,
        on_s=cfg.burst_on_s, off_s=cfg.burst_off_s,
    )

    sessions = {}
    session_regions = {}
    turns = []
    for s in range(cfg.n_sessions):
        start = next(starts)
        weights = diurnal_weights(
            start, cfg.n_regions, cfg.day_period_s, cfg.diurnal_amplitude
        )
        u = rng.random()
        acc = 0.0
        region = cfg.n_regions - 1
        for r, w in enumerate(weights):
            acc += w
            if u <= acc:
                region = r
                break
        session_id = f"s{s}"
        sessions[session_id] = rng.choice(prefixes[region])
        session_regions[session_id] = region_name(region)
        n_turns = stats.sample_pmf(rng, tables.TURNS_PER_SESSION_PMF)
        if cfg.max_turns is not None:
            n_turns = min(n_turns, cfg.max_turns)
        arrival = start
        for t in range(n_turns):
            user_len = stats.sample_length(
                rng, tables.USER_LEN_QUANTILES, cfg.length_scale
            )
            output_len = stats.sample_length(
                rng, tables.OUTPUT_LEN_QUANTILES, cfg.length_scale
            )
            turns.append(TraceTurn(
                arrival_s=round(arrival, 6),
                session=session_id,
                turn=t,
                user_len=user_len,
                output_len=output_len,
                user_text=_text(rng, user_len),
                response_text=_text(rng, output_len),
            ))
            arrival += think_time_s(
                rng, cfg.think_time_mean_s, output_len, cfg.read_s_per_unit
            )

    turns.sort(key=lambda t: (t.arrival_s, t.session, t.turn))
    return WorkloadTrace(
        workload="geo-sharegpt",
        seed=cfg.seed,
        config=cfg.as_dict(),
        tables_version=tables.TABLES_VERSION,
        sessions=sessions,
        turns=turns,
        session_regions=session_regions,
    )

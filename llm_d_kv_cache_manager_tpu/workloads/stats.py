"""Distribution sampling + fidelity validation against the committed tables.

Two jobs, deliberately in one module so they can never drift apart:

1. **Sampling** — inverse-transform draws from the piecewise-linear CDF a
   quantile table defines (`sample_quantile`) and from a discrete pmf
   (`sample_pmf`). The sharegpt generator samples through these.
2. **Validation** — KS-style distance between an empirical sample and the
   same piecewise-linear CDF (`ks_distance`), total-variation distance for
   the discrete turn pmf (`tv_distance`), and `validate_trace`, which
   checks a generated trace's prompt-length / output-length /
   turns-per-session distributions against the committed tables within
   tolerance. Used as a library self-check (bench.py --workload sharegpt
   validates its trace before serving it) and by tests/test_workloads.py.

Tolerances are sampling-noise aware: the KS critical value scales as
1/sqrt(n), and integer rounding of interpolated draws adds a small
constant distortion, so `ks_tolerance` is `slack * 1.36/sqrt(n) + eps`.
A generator bug (wrong table, uniform sampling, truncation) lands an
order of magnitude above these thresholds.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass, field
from typing import Dict, List, Sequence, Tuple

from llm_d_kv_cache_manager_tpu.workloads import tables as _tables
from llm_d_kv_cache_manager_tpu.workloads.spec import WorkloadTrace

QuantileTable = Sequence[Tuple[float, float]]
Pmf = Sequence[Tuple[int, float]]


# -- sampling ----------------------------------------------------------------


def sample_quantile(table: QuantileTable, u: float) -> float:
    """Inverse-CDF draw: piecewise-linear interpolation of the table at
    quantile `u` in [0, 1]."""
    if not 0.0 <= u <= 1.0:
        raise ValueError(f"quantile u must be in [0,1], got {u}")
    (q0, v0) = table[0]
    if u <= q0:
        return float(v0)
    for (q1, v1) in table[1:]:
        if u <= q1:
            frac = (u - q0) / (q1 - q0)
            return v0 + frac * (v1 - v0)
        q0, v0 = q1, v1
    return float(table[-1][1])


def sample_length(
    rng: random.Random, table: QuantileTable, scale: float = 1.0
) -> int:
    """Integer length draw from a quantile table, scaled (device-bench
    smoke configs shrink lengths without changing the shape), floor 1."""
    return max(1, int(round(sample_quantile(table, rng.random()) * scale)))


def sample_pmf(rng: random.Random, pmf: Pmf) -> int:
    u = rng.random()
    acc = 0.0
    for value, p in pmf:
        acc += p
        if u < acc:
            return value
    return pmf[-1][0]


# -- distances ---------------------------------------------------------------


def table_cdf(table: QuantileTable, x: float, scale: float = 1.0) -> float:
    """CDF implied by the piecewise-linear quantile table, at `x`."""
    if scale != 1.0:
        x = x / scale
    (q0, v0) = table[0]
    if x <= v0:
        return q0 if x >= v0 else 0.0
    for (q1, v1) in table[1:]:
        if x <= v1:
            if v1 == v0:
                return q1
            return q0 + (q1 - q0) * (x - v0) / (v1 - v0)
        q0, v0 = q1, v1
    return 1.0


def ks_distance(
    samples: Sequence[float], table: QuantileTable, scale: float = 1.0
) -> float:
    """sup_x |F_empirical(x) - F_table(x)|, evaluated at the sample points
    (both one-sided gaps, as in the classical KS statistic)."""
    if not samples:
        raise ValueError("ks_distance needs a non-empty sample")
    xs = sorted(samples)
    n = len(xs)
    d = 0.0
    for i, x in enumerate(xs):
        f = table_cdf(table, x, scale=scale)
        d = max(d, abs((i + 1) / n - f), abs(i / n - f))
    return d


def ks_tolerance(n: int, slack: float = 2.0, eps: float = 0.02) -> float:
    """Sampling-noise-aware KS bound: slack × the 5% critical value plus a
    constant allowance for integer rounding of interpolated draws."""
    return slack * 1.36 / math.sqrt(max(n, 1)) + eps


def tv_distance(samples: Sequence[int], pmf: Pmf) -> float:
    """Total-variation distance between the empirical pmf of `samples` and
    the committed pmf (support = union of both)."""
    if not samples:
        raise ValueError("tv_distance needs a non-empty sample")
    n = len(samples)
    emp: Dict[int, float] = {}
    for s in samples:
        emp[int(s)] = emp.get(int(s), 0.0) + 1.0 / n
    ref = {int(v): p for v, p in pmf}
    support = set(emp) | set(ref)
    return 0.5 * sum(abs(emp.get(v, 0.0) - ref.get(v, 0.0)) for v in support)


def tv_tolerance(n: int, n_categories: int, slack: float = 1.5) -> float:
    """Expected TV of an n-sample from a K-category pmf is O(sqrt(K/n));
    the floor keeps tiny smoke traces from tripping on noise."""
    return max(0.12, slack * math.sqrt(n_categories / max(n, 1)))


# -- trace validation --------------------------------------------------------


@dataclass
class Check:
    name: str
    statistic: float
    tolerance: float
    n: int

    @property
    def ok(self) -> bool:
        return self.statistic <= self.tolerance

    def as_dict(self) -> Dict:
        return {
            "statistic": round(self.statistic, 4),
            "tolerance": round(self.tolerance, 4),
            "n": self.n,
            "ok": self.ok,
        }


@dataclass
class FidelityReport:
    checks: List[Check] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return all(c.ok for c in self.checks)

    def as_dict(self) -> Dict:
        out = {c.name: c.as_dict() for c in self.checks}
        out["ok"] = self.ok
        return out

    def raise_if_failed(self) -> None:
        bad = [c for c in self.checks if not c.ok]
        if bad:
            raise ValueError(
                "workload trace failed distribution fidelity: "
                + ", ".join(
                    f"{c.name} KS/TV={c.statistic:.4f} > tol={c.tolerance:.4f}"
                    f" (n={c.n})"
                    for c in bad
                )
            )


def validate_trace(trace: WorkloadTrace) -> FidelityReport:
    """Check a sharegpt trace's empirical distributions against the
    committed tables. Honors the generator's recorded config: lengths are
    compared against tables scaled by `length_scale`, and a `max_turns`
    cap excuses the truncated tail of the turn pmf (the capped mass is
    subtracted from the expected-vs-observed gap before comparison).
    """
    if trace.workload != "sharegpt":
        raise ValueError(
            f"validate_trace checks sharegpt traces, got {trace.workload!r}"
        )
    if trace.tables_version != _tables.TABLES_VERSION:
        raise ValueError(
            f"trace was generated against tables {trace.tables_version!r}; "
            f"this build commits {_tables.TABLES_VERSION!r}"
        )
    scale = float(trace.config.get("length_scale", 1.0))
    max_turns = trace.config.get("max_turns")

    user_lens = [t.user_len for t in trace.turns]
    out_lens = [t.output_len for t in trace.turns]
    turn_counts = list(trace.turn_counts().values())

    report = FidelityReport()
    report.checks.append(Check(
        "user_len", ks_distance(user_lens, _tables.USER_LEN_QUANTILES,
                                scale=scale),
        ks_tolerance(len(user_lens)), len(user_lens),
    ))
    report.checks.append(Check(
        "output_len", ks_distance(out_lens, _tables.OUTPUT_LEN_QUANTILES,
                                  scale=scale),
        ks_tolerance(len(out_lens)), len(out_lens),
    ))

    pmf = list(_tables.TURNS_PER_SESSION_PMF)
    if max_turns is not None:
        # The generator clamps sessions at max_turns: fold the pmf's tail
        # mass onto the cap so truncation isn't misread as infidelity.
        cap = int(max_turns)
        folded: Dict[int, float] = {}
        for v, p in pmf:
            folded[min(v, cap)] = folded.get(min(v, cap), 0.0) + p
        pmf = sorted(folded.items())
    report.checks.append(Check(
        "turns_per_session", tv_distance(turn_counts, pmf),
        tv_tolerance(len(turn_counts), len(pmf)), len(turn_counts),
    ))
    return report

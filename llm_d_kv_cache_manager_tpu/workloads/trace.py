"""Canonical JSONL trace format: record/replay for workload traces.

One trace file = one header line + one line per session + one line per
turn, each a single JSON object tagged by "kind":

    {"kind": "header", "schema": "kvtpu-workload-trace/v1",
     "workload": "sharegpt", "seed": 42, "tables_version": "sharegpt-v1",
     "config": {...}}
    {"kind": "session", "id": "s0", "system_prefix": "..."}
    {"kind": "session", "id": "s1", "system_prefix": "...",
     "region": "region-2"}   # optional home-region pin (workloads.geo)
    {"kind": "turn", "arrival_s": 0.71, "session": "s0", "turn": 0,
     "user_len": 28, "output_len": 170, "user_text": "...",
     "response_text": "..."}

Turns store DELTA text (see workloads.spec): the grown prompts are derived
by `WorkloadTrace.materialize()`, so a recorded trace replays
bit-identically — `read_trace(write_trace(t)) == t`, and both benches
serve the exact same prompt stream from the same file. Sessions and turns
are written in deterministic order (session id; arrival order), so equal
traces produce byte-identical files.

Unknown "kind" lines error loudly: a trace is an input to a benchmark
headline, and silently skipping records would quietly change the measured
workload.
"""

from __future__ import annotations

import json
from typing import IO, Iterable, List, Union

from llm_d_kv_cache_manager_tpu.workloads.spec import TraceTurn, WorkloadTrace

SCHEMA = "kvtpu-workload-trace/v1"


def _dump(obj: dict) -> str:
    return json.dumps(obj, sort_keys=True, ensure_ascii=False)


def trace_lines(trace: WorkloadTrace) -> Iterable[str]:
    yield _dump({
        "kind": "header",
        "schema": SCHEMA,
        "workload": trace.workload,
        "seed": trace.seed,
        "tables_version": trace.tables_version,
        "config": trace.config,
    })
    for session_id in sorted(trace.sessions):
        rec = {
            "kind": "session",
            "id": session_id,
            "system_prefix": trace.sessions[session_id],
        }
        # Optional home region (workloads.geo). Emitted ONLY when the
        # session is pinned, so a region-free trace serializes exactly as
        # it did before the field existed — strict back-compat both ways
        # (old readers never see the key; old files round-trip byte-
        # identically through new writers).
        region = trace.session_regions.get(session_id)
        if region is not None:
            rec["region"] = region
        yield _dump(rec)
    for t in trace.turns:
        yield _dump({
            "kind": "turn",
            "arrival_s": t.arrival_s,
            "session": t.session,
            "turn": t.turn,
            "user_len": t.user_len,
            "output_len": t.output_len,
            "user_text": t.user_text,
            "response_text": t.response_text,
        })


def write_trace(trace: WorkloadTrace, path_or_file: Union[str, IO[str]]) -> None:
    if isinstance(path_or_file, str):
        with open(path_or_file, "w", encoding="utf-8") as f:
            write_trace(trace, f)
        return
    for line in trace_lines(trace):
        path_or_file.write(line + "\n")


def read_trace(path_or_file: Union[str, IO[str]]) -> WorkloadTrace:
    if isinstance(path_or_file, str):
        with open(path_or_file, encoding="utf-8") as f:
            return read_trace(f)

    header = None
    sessions = {}
    session_regions = {}
    turns: List[TraceTurn] = []
    for lineno, line in enumerate(path_or_file, start=1):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError as e:
            raise ValueError(f"trace line {lineno}: bad JSON: {e}") from e
        kind = rec.get("kind")
        if kind == "header":
            if header is not None:
                raise ValueError(f"trace line {lineno}: duplicate header")
            if rec.get("schema") != SCHEMA:
                raise ValueError(
                    f"trace line {lineno}: schema {rec.get('schema')!r} "
                    f"is not {SCHEMA!r}"
                )
            header = rec
        elif kind == "session":
            if header is None:
                raise ValueError(f"trace line {lineno}: session before header")
            sessions[rec["id"]] = rec["system_prefix"]
            if "region" in rec:
                session_regions[rec["id"]] = rec["region"]
        elif kind == "turn":
            if header is None:
                raise ValueError(f"trace line {lineno}: turn before header")
            turns.append(TraceTurn(
                arrival_s=float(rec["arrival_s"]),
                session=rec["session"],
                turn=int(rec["turn"]),
                user_len=int(rec["user_len"]),
                output_len=int(rec["output_len"]),
                user_text=rec["user_text"],
                response_text=rec["response_text"],
            ))
        else:
            raise ValueError(f"trace line {lineno}: unknown kind {kind!r}")
    if header is None:
        raise ValueError("trace has no header line")
    return WorkloadTrace(
        workload=header["workload"],
        seed=int(header["seed"]),
        config=header.get("config", {}),
        tables_version=header["tables_version"],
        sessions=sessions,
        turns=turns,
        session_regions=session_regions,
    )

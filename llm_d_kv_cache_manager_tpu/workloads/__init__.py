"""Trace-driven workload engine for the fleet benches.

The BASELINE metric is defined over a **ShareGPT replay**; this package
makes that measurable without network egress:

- `tables`    — committed ShareGPT length/turn quantile tables (vendored
                data, versioned, provenance documented).
- `sharegpt`  — deterministic multi-turn session generator matching those
                tables; conversations grow by concatenating prior turns
                (the mechanism that creates prefix-cache hits).
- `arrivals`  — open-loop Poisson / bursty ON-OFF arrival processes with
                per-session think time.
- `spec`      — the in-memory trace model (`WorkloadTrace`, delta-text
                turns, deterministic `materialize()` into full prompts).
- `trace`     — canonical JSONL record/replay (bit-identical round-trip,
                shared by bench.py and the device harness).
- `stats`     — sampling helpers + KS/TV fidelity validation of generated
                traces against the committed tables.
- `synthetic` — the historical word-salad backend (both benches' default,
                kept for artifact continuity; formerly utils/workload.py).
- `multitenant`/`geo`/`agentic` — scenario generators over the same trace
                model: Zipf tenant mixes, home-pinned diurnal regions,
                and fan-out/fan-in sub-agent sessions branching off a
                shared tool prefix (the anticipatory-prefetch bench's
                best-case replay).
- `adversarial` — the resource governor's stress diet: unique-prompt
                floods, session explosions, and churn storms with a
                deterministic pod join/leave schedule.
"""

from llm_d_kv_cache_manager_tpu.workloads.adversarial import (  # noqa: F401
    ChurnStormConfig,
    FloodConfig,
    SessionExplosionConfig,
    churn_schedule,
    generate_churn_storm,
    generate_flood,
    generate_session_explosion,
    transient_pod_name,
)
from llm_d_kv_cache_manager_tpu.workloads.agentic import (  # noqa: F401
    AgenticConfig,
    is_root,
    task_of,
)
from llm_d_kv_cache_manager_tpu.workloads.agentic import (  # noqa: F401
    generate as generate_agentic,
)
from llm_d_kv_cache_manager_tpu.workloads.geo import (  # noqa: F401
    GeoConfig,
    diurnal_weights,
    region_name,
)
from llm_d_kv_cache_manager_tpu.workloads.geo import (  # noqa: F401
    generate as generate_geo,
)
from llm_d_kv_cache_manager_tpu.workloads.multitenant import (  # noqa: F401
    MultiTenantConfig,
    tenant_of,
    tenant_weights,
)
from llm_d_kv_cache_manager_tpu.workloads.multitenant import (  # noqa: F401
    generate as generate_multitenant,
)
from llm_d_kv_cache_manager_tpu.workloads.sharegpt import (  # noqa: F401
    ShareGPTConfig,
    generate,
    uniform_control,
)
from llm_d_kv_cache_manager_tpu.workloads.spec import (  # noqa: F401
    MaterializedRequest,
    TraceTurn,
    WorkloadTrace,
)
from llm_d_kv_cache_manager_tpu.workloads.trace import (  # noqa: F401
    read_trace,
    write_trace,
)

__all__ = [
    "AgenticConfig",
    "ChurnStormConfig",
    "FloodConfig",
    "GeoConfig",
    "SessionExplosionConfig",
    "churn_schedule",
    "generate_churn_storm",
    "generate_flood",
    "generate_session_explosion",
    "transient_pod_name",
    "MultiTenantConfig",
    "ShareGPTConfig",
    "diurnal_weights",
    "generate",
    "generate_agentic",
    "generate_geo",
    "generate_multitenant",
    "is_root",
    "region_name",
    "task_of",
    "tenant_of",
    "tenant_weights",
    "uniform_control",
    "MaterializedRequest",
    "TraceTurn",
    "WorkloadTrace",
    "read_trace",
    "write_trace",
]

"""Multi-tenant ShareGPT-shaped workload: T tenants, Zipf popularity.

The placement bench's scenario (ROADMAP "Hot-prefix replication and
predictive placement"): thousands of tenants share a fleet, each with its
own system prefix, and tenant popularity is heavy-tailed — a handful of hot
tenants carry most of the traffic. Precise prefix routing concentrates each
tenant on the pod that happens to hold its prefix; under a Zipf mix that
turns the hot tenants' pods into hotspots while the rest of the fleet
idles. This generator produces exactly that shape, deterministically:

- every tenant `t` gets a system prefix sampled from the committed
  ShareGPT prefix-length table, and a stable **LoRA keyspace id** (`t`
  itself) so per-tenant cache isolation rides the real extra-key machinery
  in `hashing.py`, not just distinct prefix text;
- sessions draw their tenant from a Zipf(s) distribution (`zipf_s=0` is
  the uniform control mix — the "no hotspot" yardstick the placement bench
  measures retention against);
- turn counts and user/output lengths come from the same committed
  ShareGPT tables as the single-tenant generator, arrivals are open-loop.

Session ids encode their tenant (``t<k>-s<n>``), so the tenant of any
materialized request — and hence its lora/keyspace id — is derivable from
the trace alone and survives the JSONL record/replay round trip unchanged.
"""

from __future__ import annotations

import dataclasses
import random
from dataclasses import dataclass
from typing import List, Optional

from llm_d_kv_cache_manager_tpu.workloads import stats, tables
from llm_d_kv_cache_manager_tpu.workloads.arrivals import (
    arrival_process,
    think_time_s,
)
from llm_d_kv_cache_manager_tpu.workloads.spec import TraceTurn, WorkloadTrace
from llm_d_kv_cache_manager_tpu.workloads.synthetic import text as _text


@dataclass(frozen=True)
class MultiTenantConfig:
    """Knobs of the multi-tenant generator (recorded in the trace header)."""

    n_tenants: int = 24
    n_sessions: int = 96
    seed: int = 42
    # Tenant-popularity skew: session tenants draw from P(k) ∝ 1/(k+1)^s.
    # 0.0 = uniform (the control mix); ~1.5+ = a pronounced hotspot where
    # the top tenant carries a large constant fraction of all sessions.
    zipf_s: float = 0.0
    # Session-start arrival process and per-session think time.
    arrival: str = "poisson"
    session_rate_per_s: float = 3.0
    burst_on_s: float = 10.0
    burst_off_s: float = 20.0
    think_time_mean_s: float = 4.0
    read_s_per_unit: float = 0.005
    # Per-tenant prefix length scale over the committed prefix table
    # (1.0 = table-faithful) and per-turn length scale.
    prefix_length_scale: float = 1.0
    # Fixed per-tenant prefix length in words; overrides the table draw
    # when set. The placement bench pins this so hotspot dynamics measure
    # the MIX, not the prefix-length lottery of whichever tenant the Zipf
    # head landed on.
    prefix_words: Optional[int] = None
    length_scale: float = 1.0
    # Turn cap (the pmf's marathon tail would let one session dominate).
    max_turns: Optional[int] = 6

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def tenant_weights(n_tenants: int, zipf_s: float) -> List[float]:
    """Normalized Zipf(s) popularity over tenants 0..n-1 (0 = hottest)."""
    raw = [1.0 / ((k + 1) ** zipf_s) for k in range(n_tenants)]
    total = sum(raw)
    return [w / total for w in raw]


def tenant_of(session_id: str) -> int:
    """Tenant index encoded in a session id (``t<k>-s<n>``)."""
    return int(session_id.split("-", 1)[0][1:])


def _draw(rng: random.Random, cum_weights: List[float]) -> int:
    u = rng.random()
    lo, hi = 0, len(cum_weights) - 1
    while lo < hi:
        mid = (lo + hi) // 2
        if u <= cum_weights[mid]:
            hi = mid
        else:
            lo = mid + 1
    return lo


def generate(config: Optional[MultiTenantConfig] = None) -> WorkloadTrace:
    """Build the multi-tenant trace. Deterministic in (config, seed)."""
    cfg = config or MultiTenantConfig()
    if cfg.n_tenants <= 0:
        raise ValueError("n_tenants must be >= 1")
    if cfg.zipf_s < 0:
        raise ValueError("zipf_s must be >= 0")
    rng = random.Random(cfg.seed)

    # Tenant prefixes first, in tenant order (fixed draw order).
    prefixes = []
    for t in range(cfg.n_tenants):
        n = cfg.prefix_words
        if n is None:
            n = stats.sample_length(
                rng, tables.SYSTEM_PREFIX_LEN_QUANTILES,
                cfg.prefix_length_scale,
            )
        prefixes.append(f"[tenant {t}] " + _text(rng, n))

    weights = tenant_weights(cfg.n_tenants, cfg.zipf_s)
    cum = []
    acc = 0.0
    for w in weights:
        acc += w
        cum.append(acc)
    cum[-1] = 1.0

    starts = arrival_process(
        cfg.arrival, rng, cfg.session_rate_per_s,
        on_s=cfg.burst_on_s, off_s=cfg.burst_off_s,
    )

    sessions = {}
    turns = []
    for s in range(cfg.n_sessions):
        tenant = _draw(rng, cum)
        session_id = f"t{tenant}-s{s}"
        start = next(starts)
        sessions[session_id] = prefixes[tenant]
        n_turns = stats.sample_pmf(rng, tables.TURNS_PER_SESSION_PMF)
        if cfg.max_turns is not None:
            n_turns = min(n_turns, cfg.max_turns)
        arrival = start
        for t in range(n_turns):
            user_len = stats.sample_length(
                rng, tables.USER_LEN_QUANTILES, cfg.length_scale
            )
            output_len = stats.sample_length(
                rng, tables.OUTPUT_LEN_QUANTILES, cfg.length_scale
            )
            turns.append(TraceTurn(
                arrival_s=round(arrival, 6),
                session=session_id,
                turn=t,
                user_len=user_len,
                output_len=output_len,
                user_text=_text(rng, user_len),
                response_text=_text(rng, output_len),
            ))
            arrival += think_time_s(
                rng, cfg.think_time_mean_s, output_len, cfg.read_s_per_unit
            )

    turns.sort(key=lambda t: (t.arrival_s, t.session, t.turn))
    return WorkloadTrace(
        workload="multitenant-sharegpt",
        seed=cfg.seed,
        config=cfg.as_dict(),
        tables_version=tables.TABLES_VERSION,
        sessions=sessions,
        turns=turns,
    )

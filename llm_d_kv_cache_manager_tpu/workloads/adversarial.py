"""Adversarial trace generators: the resource governor's stress diet.

Every other generator in this package models a workload the caches were
BUILT for — shared prefixes, returning sessions, stable fleets. These
three model the workloads that kill an ungoverned control plane, each
aimed at a different stateful structure:

- **Unique-prompt flood** (`generate_flood`): every request is a fresh
  single-turn session with a never-repeating prompt. Zero reuse means
  every byte the chain memo, prefix store, and index retain for it is
  pure waste — the structure-growth worst case the governor's byte
  budget exists to cap (and the arm where shedding costs no hits,
  because there were never going to be any).
- **Session explosion** (`generate_session_explosion`): a storm of
  short-lived sessions, far more than any session table's capacity,
  each abandoned after a turn or two. Per-session state (prediction
  records, popularity credit) grows with the number of sessions EVER
  seen unless something sheds the dead tail.
- **Churn storm** (`generate_churn_storm` + `churn_schedule`): a
  moderate, cache-friendly workload over a fleet whose pods join and
  leave continuously. The trace itself is ordinary on purpose — the
  adversary is the roster: per-pod rows (fleet health, load,
  anti-entropy trust, transfer breakers) must track the LIVE pods, not
  every pod that ever existed. The deterministic join/leave schedule is
  derived from the same config so bench arms replay it exactly.

Like every generator here, outputs are plain `WorkloadTrace`s — pure
functions of (config, seed), one `random.Random(seed)` in fixed draw
order, delta-text turns — so JSONL record/replay is bit-identical by
construction.
"""

from __future__ import annotations

import dataclasses
import random
from dataclasses import dataclass
from typing import List, Optional, Tuple

from llm_d_kv_cache_manager_tpu.workloads import tables
from llm_d_kv_cache_manager_tpu.workloads.arrivals import arrival_process
from llm_d_kv_cache_manager_tpu.workloads.spec import TraceTurn, WorkloadTrace
from llm_d_kv_cache_manager_tpu.workloads.synthetic import text as _text


@dataclass(frozen=True)
class FloodConfig:
    """Knobs of the unique-prompt flood (recorded in the trace header)."""

    n_requests: int = 400
    seed: int = 42
    arrival: str = "poisson"
    rate_per_s: float = 4.0
    burst_on_s: float = 10.0
    burst_off_s: float = 20.0
    # Every prompt is long enough to span many blocks — each request
    # plants a full chain of never-again-touched index entries.
    prompt_words: int = 600
    response_words: int = 60

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def generate_flood(config: Optional[FloodConfig] = None) -> WorkloadTrace:
    """Unique single-turn sessions; no two prompts share a prefix."""
    cfg = config or FloodConfig()
    if cfg.n_requests <= 0:
        raise ValueError("n_requests must be >= 1")
    rng = random.Random(cfg.seed)
    starts = arrival_process(
        cfg.arrival, rng, cfg.rate_per_s,
        on_s=cfg.burst_on_s, off_s=cfg.burst_off_s,
    )
    sessions = {}
    turns: List[TraceTurn] = []
    for k in range(cfg.n_requests):
        session_id = f"f{k}"
        # A per-request tag makes the very first block unique: no two
        # floods share even their opening words, so nothing — memo,
        # prefix store, index chain — is reusable across requests.
        sessions[session_id] = ""
        user = f"[flood {k}] " + _text(rng, cfg.prompt_words)
        resp = _text(rng, cfg.response_words)
        turns.append(TraceTurn(
            arrival_s=round(next(starts), 6),
            session=session_id,
            turn=0,
            user_len=len(user.split()),
            output_len=len(resp.split()),
            user_text=user,
            response_text=resp,
        ))
    turns.sort(key=lambda t: (t.arrival_s, t.session, t.turn))
    return WorkloadTrace(
        workload="adversarial_flood",
        seed=cfg.seed,
        config=cfg.as_dict(),
        tables_version=tables.TABLES_VERSION,
        sessions=sessions,
        turns=turns,
    )


@dataclass(frozen=True)
class SessionExplosionConfig:
    """Knobs of the session explosion (recorded in the trace header)."""

    n_sessions: int = 600
    seed: int = 42
    arrival: str = "bursty"
    rate_per_s: float = 6.0
    burst_on_s: float = 5.0
    burst_off_s: float = 10.0
    # A small shared preamble pool keeps SOME block reuse alive (this
    # storm attacks per-session state, not the block caches), while the
    # 1-2 turn lifetime guarantees almost every session is dead weight
    # the moment its last turn lands.
    n_prefixes: int = 4
    prefix_words: int = 200
    max_turns: int = 2
    think_time_mean_s: float = 2.0
    user_words: int = 40
    response_words: int = 50

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def generate_session_explosion(
    config: Optional[SessionExplosionConfig] = None,
) -> WorkloadTrace:
    """Short-lived session storm: per-session state's worst case."""
    cfg = config or SessionExplosionConfig()
    if cfg.n_sessions <= 0:
        raise ValueError("n_sessions must be >= 1")
    if cfg.max_turns <= 0:
        raise ValueError("max_turns must be >= 1")
    rng = random.Random(cfg.seed)
    prefixes = [
        f"[pool {g}] " + _text(rng, cfg.prefix_words)
        for g in range(max(cfg.n_prefixes, 1))
    ]
    starts = arrival_process(
        cfg.arrival, rng, cfg.rate_per_s,
        on_s=cfg.burst_on_s, off_s=cfg.burst_off_s,
    )
    sessions = {}
    turns: List[TraceTurn] = []
    for k in range(cfg.n_sessions):
        session_id = f"x{k}"
        sessions[session_id] = prefixes[k % len(prefixes)]
        at = next(starts)
        n_turns = 1 + rng.randrange(cfg.max_turns)
        for t in range(n_turns):
            user = _text(rng, cfg.user_words)
            resp = _text(rng, cfg.response_words)
            turns.append(TraceTurn(
                arrival_s=round(at, 6),
                session=session_id,
                turn=t,
                user_len=len(user.split()),
                output_len=len(resp.split()),
                user_text=user,
                response_text=resp,
            ))
            at += rng.expovariate(1.0 / cfg.think_time_mean_s)
    turns.sort(key=lambda t: (t.arrival_s, t.session, t.turn))
    return WorkloadTrace(
        workload="adversarial_sessions",
        seed=cfg.seed,
        config=cfg.as_dict(),
        tables_version=tables.TABLES_VERSION,
        sessions=sessions,
        turns=turns,
    )


@dataclass(frozen=True)
class ChurnStormConfig:
    """Knobs of the churn storm (recorded in the trace header)."""

    seed: int = 42
    # The REQUEST side stays deliberately cache-friendly: the adversary
    # is the roster, and a friendly workload makes any hit-rate damage
    # attributable to churn handling alone.
    n_groups: int = 4
    users_per_group: int = 6
    prefix_words: int = 400
    turns_per_session: int = 4
    arrival: str = "poisson"
    rate_per_s: float = 2.0
    burst_on_s: float = 10.0
    burst_off_s: float = 20.0
    think_time_mean_s: float = 3.0
    user_words: int = 40
    response_words: int = 60
    # The roster side: `base_pods` serve from t=0 and never leave;
    # every `churn_interval_s` one transient pod joins and the oldest
    # transient pod leaves, for `n_churn_events` join/leave pairs —
    # steady-state live count is `base_pods + transient_pods`, while
    # the EVER-SEEN count grows by one pod per event (the leak the
    # reaper exists to stop).
    base_pods: int = 2
    transient_pods: int = 2
    n_churn_events: int = 24
    churn_interval_s: float = 8.0

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def transient_pod_name(index: int) -> str:
    """Roster name of the index-th transient pod (join order)."""
    return f"churn-{index}"


def churn_schedule(
    config: Optional[ChurnStormConfig] = None,
) -> List[Tuple[float, str, str]]:
    """The deterministic roster script: time-ordered
    ``(t_s, "join" | "leave", pod_name)`` events, a pure function of the
    config (no RNG — replaying arms must agree on the roster exactly).

    The first `transient_pods` joins have no matching leave until the
    pipeline fills; thereafter each interval is one join + one leave of
    the oldest transient, so the live transient count holds constant
    while names never repeat.
    """
    cfg = config or ChurnStormConfig()
    if cfg.transient_pods <= 0 or cfg.n_churn_events < 0:
        raise ValueError(
            f"invalid churn shape: transient_pods={cfg.transient_pods} "
            f"n_churn_events={cfg.n_churn_events}"
        )
    events: List[Tuple[float, str, str]] = []
    for i in range(cfg.n_churn_events):
        at = round((i + 1) * cfg.churn_interval_s, 6)
        events.append((at, "join", transient_pod_name(i)))
        if i >= cfg.transient_pods:
            events.append(
                (at, "leave", transient_pod_name(i - cfg.transient_pods))
            )
    return events


def generate_churn_storm(
    config: Optional[ChurnStormConfig] = None,
) -> WorkloadTrace:
    """Cache-friendly request stream for the churn-storm scenario; the
    roster events come from `churn_schedule` over the same config."""
    cfg = config or ChurnStormConfig()
    if cfg.n_groups <= 0 or cfg.users_per_group <= 0:
        raise ValueError(
            f"invalid shape: n_groups={cfg.n_groups} "
            f"users_per_group={cfg.users_per_group}"
        )
    rng = random.Random(cfg.seed)
    prefixes = [
        f"[churn group {g}] " + _text(rng, cfg.prefix_words)
        for g in range(cfg.n_groups)
    ]
    starts = arrival_process(
        cfg.arrival, rng, cfg.rate_per_s,
        on_s=cfg.burst_on_s, off_s=cfg.burst_off_s,
    )
    sessions = {}
    turns: List[TraceTurn] = []
    for g in range(cfg.n_groups):
        for u in range(cfg.users_per_group):
            session_id = f"c{g}-u{u}"
            sessions[session_id] = prefixes[g]
            at = next(starts)
            for t in range(cfg.turns_per_session):
                user = _text(rng, cfg.user_words)
                resp = _text(rng, cfg.response_words)
                turns.append(TraceTurn(
                    arrival_s=round(at, 6),
                    session=session_id,
                    turn=t,
                    user_len=len(user.split()),
                    output_len=len(resp.split()),
                    user_text=user,
                    response_text=resp,
                ))
                at += rng.expovariate(1.0 / cfg.think_time_mean_s)
    turns.sort(key=lambda t: (t.arrival_s, t.session, t.turn))
    return WorkloadTrace(
        workload="adversarial_churn",
        seed=cfg.seed,
        config=cfg.as_dict(),
        tables_version=tables.TABLES_VERSION,
        sessions=sessions,
        turns=turns,
    )

"""Canonical in-memory trace model for the workload engine.

A workload — generated (workloads.sharegpt) or replayed from JSONL
(workloads.trace) — is a `WorkloadTrace`: per-session shared system
prefixes plus a time-ordered stream of `TraceTurn`s. Turns carry DELTA
text (the new user message and the scripted assistant response), never the
full grown prompt: the grown prompt for turn t of a session is derived
deterministically by `materialize()`, which concatenates the session's
prior turns exactly the way both benches do ("... [user] q" then
"... [assistant] r"). Storing deltas keeps the JSONL linear in
conversation length instead of quadratic, and makes record→replay
bit-identical by construction: the prompt stream is a pure function of the
trace content.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple


@dataclass(frozen=True)
class TraceTurn:
    """One request of the workload, in arrival order.

    `user_len` / `output_len` are the sampled lengths (length units — the
    word counts of `user_text` / `response_text`); they are recorded
    explicitly so distribution validation (workloads.stats) never has to
    re-derive them from text.
    """

    arrival_s: float
    session: str
    turn: int
    user_len: int
    output_len: int
    user_text: str
    response_text: str


@dataclass(frozen=True)
class MaterializedRequest:
    """A served request: the fully grown prompt for one trace turn.

    `region` is the session's home region when the trace pins one
    (workloads.geo), else None — trailing default, so every pre-geo
    construction site is untouched.
    """

    arrival_s: float
    session: str
    turn: int
    prompt: str
    output_len: int
    region: Optional[str] = None


@dataclass
class WorkloadTrace:
    workload: str  # "sharegpt" | "synthetic" | ...
    seed: int
    config: Dict  # JSON-serializable generator config (provenance)
    tables_version: str
    # session id -> shared system prefix text ("" when the session has none)
    sessions: Dict[str, str] = field(default_factory=dict)
    turns: List[TraceTurn] = field(default_factory=list)
    # session id -> home region (workloads.geo). Sparse and strictly
    # optional: traces recorded before the geo workload carry no regions,
    # read back with this empty, and re-serialize byte-identically.
    session_regions: Dict[str, str] = field(default_factory=dict)

    def materialize(self) -> Iterator[MaterializedRequest]:
        """Yield the full-prompt request stream in arrival order.

        Deterministic: prompts are a pure function of the trace, so two
        materializations of equal traces are identical — the property the
        record/replay round-trip test pins.
        """
        history: Dict[str, str] = dict(self.sessions)
        for t in self.turns:
            prompt = history[t.session] + " [user] " + t.user_text
            yield MaterializedRequest(
                arrival_s=t.arrival_s,
                session=t.session,
                turn=t.turn,
                prompt=prompt,
                output_len=t.output_len,
                region=self.session_regions.get(t.session),
            )
            history[t.session] = prompt + " [assistant] " + t.response_text

    def requests(self) -> List[MaterializedRequest]:
        return list(self.materialize())

    def turn_counts(self) -> Dict[str, int]:
        """Turns per session, for distribution validation."""
        counts: Dict[str, int] = {}
        for t in self.turns:
            counts[t.session] = max(counts.get(t.session, 0), t.turn + 1)
        return counts

    def sorted_key(self) -> List[Tuple[float, str, int]]:
        return [(t.arrival_s, t.session, t.turn) for t in self.turns]

"""Agentic trace generator: branching sessions off a shared tool prefix.

The ROADMAP's agentic scenario, and the best case for every prefix plane
in the system — routing, replication, and above all anticipatory prefetch:

- **One large shared preamble.** Every task starts from a big tool/system
  prefix (the tool schemas + instructions an agent framework prepends to
  every call), drawn from a small set of toolsets — the fleet-wide shared
  prefix that precise routing and hot-prefix replication feast on.
- **Fan-out.** After the root agent's planning turn, `fan_out` sub-agents
  branch **off the root's grown prompt**: each sub-agent session's system
  prefix IS the root conversation so far, so the branch point is a shared
  prefix of every worker — one pod warming it serves the whole wave.
- **Tight tool loops.** Each sub-agent runs `subagent_turns` tool-call
  iterations whose gaps are short and regular (tool latency, not human
  think time) — exactly the high-predictability cadence a session
  predictor's ETA model converges on fastest.
- **Fan-in.** When a phase's workers finish, the root continues with a
  synthesis turn extending its own chain; later phases branch again from
  the longer prompt.

Like every generator here, the output is a plain `WorkloadTrace`: a pure
function of (config, seed) — one `random.Random(seed)` drives every draw
in a fixed order — with delta-text turns, so JSONL record/replay is
bit-identical by construction and both benches serve the same prompt
stream. Sub-agent branching needs nothing new from the trace model: a
branch is just a session whose system prefix equals the parent's grown
prompt, built with the exact concatenation `materialize()` performs.
"""

from __future__ import annotations

import dataclasses
import random
from dataclasses import dataclass
from typing import List, Optional

from llm_d_kv_cache_manager_tpu.workloads.arrivals import arrival_process
from llm_d_kv_cache_manager_tpu.workloads.spec import TraceTurn, WorkloadTrace
from llm_d_kv_cache_manager_tpu.workloads.synthetic import text as _text
from llm_d_kv_cache_manager_tpu.workloads import tables


@dataclass(frozen=True)
class AgenticConfig:
    """Knobs of the agentic generator (recorded in the trace header)."""

    n_tasks: int = 24
    seed: int = 42
    # Task (root-agent) arrival process.
    arrival: str = "poisson"
    task_rate_per_s: float = 0.8
    burst_on_s: float = 10.0
    burst_off_s: float = 20.0
    # Shared tool/system preambles: every task draws one of
    # `n_tool_prefixes` toolsets round-robin, each `tool_prefix_words`
    # long — the large fleet-shared prefix.
    n_tool_prefixes: int = 2
    tool_prefix_words: int = 1100
    # Fan-out/fan-in shape: phases per task, sub-agents per phase, tool
    # iterations per sub-agent.
    n_phases: int = 2
    fan_out: int = 3
    subagent_turns: int = 3
    # Timing: sub-agents dispatch shortly after the root's planning turn
    # (staggered), iterate at tool latency, and the root synthesizes a
    # beat after the slowest worker.
    dispatch_delay_s: float = 0.4
    worker_stagger_s: float = 0.2
    tool_latency_mean_s: float = 1.2
    synthesis_think_s: float = 2.5
    # Mean word counts (each draw jittered ±30% for realistic spread).
    task_words: int = 70
    plan_words: int = 90
    subtask_words: int = 35
    tool_call_words: int = 55
    tool_result_words: int = 80
    synthesis_request_words: int = 45
    synthesis_words: int = 140

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def _jitter(rng: random.Random, mean_words: int) -> int:
    """Deterministic ±30% spread around a mean word count."""
    return max(4, int(mean_words * (0.7 + 0.6 * rng.random())))


def task_of(session_id: str) -> int:
    """Task index encoded in a session id (``a<k>-root`` /
    ``a<k>-p<p>-w<j>``)."""
    return int(session_id.split("-", 1)[0][1:])


def is_root(session_id: str) -> bool:
    return session_id.endswith("-root")


def generate(config: Optional[AgenticConfig] = None) -> WorkloadTrace:
    """Build the agentic trace. Deterministic in (config, seed)."""
    cfg = config or AgenticConfig()
    if cfg.n_tasks <= 0:
        raise ValueError("n_tasks must be >= 1")
    if cfg.fan_out <= 0 or cfg.n_phases < 0 or cfg.subagent_turns <= 0:
        raise ValueError(
            f"invalid agent shape: fan_out={cfg.fan_out} "
            f"n_phases={cfg.n_phases} subagent_turns={cfg.subagent_turns}"
        )
    rng = random.Random(cfg.seed)

    # Toolset preambles first, in fixed draw order.
    tool_prefixes = [
        f"[toolset {g}] " + _text(rng, cfg.tool_prefix_words)
        for g in range(max(cfg.n_tool_prefixes, 1))
    ]

    starts = arrival_process(
        cfg.arrival, rng, cfg.task_rate_per_s,
        on_s=cfg.burst_on_s, off_s=cfg.burst_off_s,
    )

    sessions = {}
    turns: List[TraceTurn] = []

    def emit(session: str, turn: int, at: float, user: str, resp: str):
        turns.append(TraceTurn(
            arrival_s=round(at, 6),
            session=session,
            turn=turn,
            user_len=len(user.split()),
            output_len=len(resp.split()),
            user_text=user,
            response_text=resp,
        ))

    for k in range(cfg.n_tasks):
        root_id = f"a{k}-root"
        prefix = tool_prefixes[k % len(tool_prefixes)]
        sessions[root_id] = prefix
        start = next(starts)

        # Root planning turn. `grown` mirrors materialize()'s exact
        # concatenation — it becomes the sub-agents' branch prefix.
        task_text = _text(rng, _jitter(rng, cfg.task_words))
        plan_text = _text(rng, _jitter(rng, cfg.plan_words))
        emit(root_id, 0, start, task_text, plan_text)
        grown = (
            prefix + " [user] " + task_text + " [assistant] " + plan_text
        )

        root_turn = 1
        root_at = start
        for p in range(cfg.n_phases):
            # Fan-out: each worker branches off the root's grown prompt.
            phase_end = root_at
            for j in range(cfg.fan_out):
                worker_id = f"a{k}-p{p}-w{j}"
                sessions[worker_id] = grown
                at = (
                    root_at
                    + cfg.dispatch_delay_s
                    + j * cfg.worker_stagger_s
                    + rng.expovariate(1.0 / max(cfg.worker_stagger_s, 1e-6))
                )
                for t in range(cfg.subagent_turns):
                    user = _text(rng, _jitter(
                        rng,
                        cfg.subtask_words if t == 0 else cfg.tool_result_words,
                    ))
                    resp = _text(rng, _jitter(rng, cfg.tool_call_words))
                    emit(worker_id, t, at, user, resp)
                    at += rng.expovariate(1.0 / cfg.tool_latency_mean_s)
                # `at` now points one tool latency past the worker's last
                # turn — when its final answer is in hand.
                phase_end = max(phase_end, at)
            # Fan-in: the root synthesizes after the slowest worker.
            root_at = phase_end + cfg.synthesis_think_s + rng.expovariate(
                1.0 / cfg.synthesis_think_s
            )
            syn_req = _text(rng, _jitter(rng, cfg.synthesis_request_words))
            syn_resp = _text(rng, _jitter(rng, cfg.synthesis_words))
            emit(root_id, root_turn, root_at, syn_req, syn_resp)
            grown = grown + " [user] " + syn_req + " [assistant] " + syn_resp
            root_turn += 1

    turns.sort(key=lambda t: (t.arrival_s, t.session, t.turn))
    return WorkloadTrace(
        workload="agentic",
        seed=cfg.seed,
        config=cfg.as_dict(),
        tables_version=tables.TABLES_VERSION,
        sessions=sessions,
        turns=turns,
    )

#!/usr/bin/env bash
# Pre-commit hook: lint + full test suite before every commit.
# Install with `make precommit-install`.
# Parity: /root/reference/hooks/pre-commit.sh:18-23 (make lint + make test).
set -e

echo "Running lint..."
make lint

echo "Running tests..."
make test

echo "All checks passed."
